"""Rendezvous message transport: native (C++) with pure-Python fallback.

Wire format (shared by both implementations):
  handshake: u32 BE magic 0x44594E4D ("DYNM") + 64-byte NUL-padded key
  messages:  u64 BE length + payload

The rendezvous shape mirrors the reference's NIXL bootstrap contract — the
decode side dials the prefill side's `--disaggregation-bootstrap-port` and
identifies the transfer by key
(/root/reference/examples/deploy/sglang/disagg.yaml:47-52).
"""

from __future__ import annotations

import ctypes
import socket
import struct
from typing import Optional, Tuple

from dynamo_tpu.runtime.native import get_lib

MAGIC = 0x44594E4D
KEY_LEN = 64
HANDSHAKE_TIMEOUT_S = 10.0


class Connection:
    """One established transfer connection (either side)."""

    def __init__(self, fd: Optional[int] = None, sock: Optional[socket.socket] = None):
        self._fd = fd
        self._sock = sock
        self._lib = get_lib() if fd is not None else None

    # ------------------------------------------------------------- sending --
    def send_msg(self, data) -> None:
        data = bytes(data) if not isinstance(data, (bytes, bytearray, memoryview)) else data
        if self._fd is not None:
            buf = (ctypes.c_char * len(data)).from_buffer_copy(data)
            if self._lib.dt_send_msg(self._fd, buf, len(data)) != 0:
                raise ConnectionError("native send failed")
        else:
            self._sock.sendall(struct.pack(">Q", len(data)))
            self._sock.sendall(data)

    def recv_msg(self, max_len: int = 1 << 34) -> bytes:
        if self._fd is not None:
            n = self._lib.dt_recv_len(self._fd)
            if n < 0 or n > max_len:
                raise ConnectionError(f"native recv failed (len={n})")
            buf = ctypes.create_string_buffer(n)
            if self._lib.dt_recv_into(self._fd, buf, n) != 0:
                raise ConnectionError("native recv payload failed")
            return buf.raw
        else:
            hdr = self._recv_exact(8)
            (n,) = struct.unpack(">Q", hdr)
            if n > max_len:
                raise ConnectionError(f"message too large: {n}")
            return self._recv_exact(n)

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        while n > 0:
            c = self._sock.recv(min(n, 1 << 20))
            if not c:
                raise ConnectionError("peer closed")
            chunks.append(c)
            n -= len(c)
        return b"".join(chunks)

    def close(self):
        if self._fd is not None:
            get_lib().dt_close(self._fd)
            self._fd = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None


class Listener:
    """Bootstrap listener (prefill-worker side)."""

    def __init__(self, port: int = 0, prefer_native: bool = True):
        self._lib = get_lib() if prefer_native else None
        if self._lib is not None:
            port_out = ctypes.c_uint16(0)
            fd = self._lib.dt_listen(port, ctypes.byref(port_out))
            if fd < 0:
                raise OSError(f"dt_listen({port}) failed")
            self._fd = fd
            self._sock = None
            self.port = port_out.value
        else:
            self._fd = None
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            s.bind(("0.0.0.0", port))
            s.listen(64)
            self._sock = s
            self.port = s.getsockname()[1]

    def accept(self, timeout_ms: int = -1) -> Tuple[Connection, str]:
        """Accept one transfer connection; returns (conn, rendezvous_key)."""
        if self._fd is not None:
            keybuf = ctypes.create_string_buffer(KEY_LEN + 1)
            fd = self._lib.dt_accept(self._fd, keybuf, timeout_ms)
            if fd == -2:
                raise TimeoutError("accept timed out")
            if fd < 0:
                raise ConnectionError("accept failed")
            return Connection(fd=fd), keybuf.value.decode(errors="replace")
        else:
            self._sock.settimeout(timeout_ms / 1000 if timeout_ms >= 0 else None)
            try:
                s, _ = self._sock.accept()
            except socket.timeout:
                raise TimeoutError("accept timed out")
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # bound the handshake so a silent dialer can't wedge the accept
            # loop; cleared once the peer has identified itself
            s.settimeout(HANDSHAKE_TIMEOUT_S)
            try:
                hdr = s.recv(4, socket.MSG_WAITALL)
                if len(hdr) != 4 or struct.unpack(">I", hdr)[0] != MAGIC:
                    raise ConnectionError("bad handshake magic")
                key = s.recv(KEY_LEN, socket.MSG_WAITALL)
                if len(key) != KEY_LEN:
                    raise ConnectionError("short handshake key")
            except socket.timeout:
                s.close()
                raise ConnectionError("handshake timed out")
            except ConnectionError:
                s.close()
                raise
            s.settimeout(None)
            return Connection(sock=s), key.rstrip(b"\x00").decode(errors="replace")

    def close(self):
        if self._fd is not None:
            get_lib().dt_close(self._fd)
            self._fd = None
        if self._sock is not None:
            self._sock.close()
            self._sock = None


def connect(host: str, port: int, key: str,
            prefer_native: bool = True) -> Connection:
    lib = get_lib() if prefer_native else None
    if lib is not None:
        fd = lib.dt_connect(host.encode(), port, key.encode()[: KEY_LEN - 1])
        if fd < 0:
            raise ConnectionError(f"dt_connect({host}:{port}) failed")
        return Connection(fd=fd)
    s = socket.create_connection((host, port), timeout=30)
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    s.settimeout(None)
    keyb = key.encode()[:KEY_LEN].ljust(KEY_LEN, b"\x00")
    s.sendall(struct.pack(">I", MAGIC) + keyb)
    return Connection(sock=s)
