"""KV-cache handoff between prefill and decode workers.

Two data planes, selected by `--disaggregation-transfer-backend`
(mirroring /root/reference/examples/deploy/sglang/disagg.yaml:47-48):

- "ici": both roles share a process/slice — the handoff is a device-to-device
  page copy placed by XLA over ICI (`Engine.export_kv`/`import_kv` on
  jax.Arrays; no host roundtrip when src/dst shardings are compatible).
  Used by the colocated topology and by in-process tests.
- "dcn": cross-host — pages serialize to bytes and stream over the native
  transport (transfer.transport), with NIXL-style key rendezvous on the
  prefill worker's bootstrap port.

Wire schema (dcn): one message = JSON header (dtype/shape/n_tokens/first_token)
+ one message per tensor (k then v, raw bytes, C-order).
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from dynamo_tpu.transfer import transport

log = logging.getLogger("dynamo_tpu.kv_transfer")


def _tobytes(arr: np.ndarray) -> bytes:
    # bfloat16 has no numpy dtype string; ship raw bytes + jax dtype name
    return np.ascontiguousarray(arr).view(np.uint8).tobytes()


def _dtype_name(arr) -> str:
    return str(arr.dtype)


def _frombytes(data: bytes, dtype: str, shape) -> np.ndarray:
    if dtype == "bfloat16":
        import ml_dtypes

        np_dtype = np.dtype(ml_dtypes.bfloat16)
    else:
        np_dtype = np.dtype(dtype)
    return np.frombuffer(data, dtype=np_dtype).reshape(shape)


class KVSource:
    """Prefill-worker side: holds exported KV until the decode side pulls it.

    One accept thread serves the bootstrap port; each parked request is keyed
    by request_id. After a successful pull (or expiry) the engine's parked
    pages are released."""

    def __init__(self, engine, port: int = 0, parked_ttl_s: float = 120.0):
        self.engine = engine
        self.parked_ttl_s = parked_ttl_s
        self.listener = transport.Listener(port)
        self.port = self.listener.port
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="kv-source")
        self._thread.start()

    def close(self):
        self._stop = True
        self.listener.close()

    def _serve(self):
        last_expiry = 0.0
        while not self._stop:
            import time as _time

            now = _time.monotonic()
            if now - last_expiry > 10.0:
                # reclaim KV parked for peers that never pulled (crash / lost
                # ack) so failures can't bleed the page pool dry
                self.engine.expire_parked(self.parked_ttl_s)
                last_expiry = now
            try:
                conn, key = self.listener.accept(timeout_ms=500)
            except TimeoutError:
                continue
            except Exception:
                if self._stop:
                    return
                log.exception("kv-source accept failed")
                continue
            threading.Thread(
                target=self._handle, args=(conn, key), daemon=True
            ).start()

    def _handle(self, conn: transport.Connection, request_id: str):
        try:
            k, v, n_tokens = self.engine.export_kv(request_id)
            header = {
                "request_id": request_id,
                "n_tokens": n_tokens,
                "dtype": _dtype_name(k),
                "shape": list(k.shape),
            }
            conn.send_msg(json.dumps(header).encode())
            conn.send_msg(_tobytes(k))
            conn.send_msg(_tobytes(v))
            # wait for ack so pages outlive a mid-transfer failure
            ack = conn.recv_msg(max_len=64)
            if ack == b"OK":
                self.engine.release_parked(request_id)
        except KeyError:
            try:
                conn.send_msg(json.dumps({"error": "unknown request"}).encode())
            except Exception:
                pass
        except Exception:
            log.exception("kv transfer for %s failed", request_id)
        finally:
            conn.close()


def fetch_kv(host: str, port: int, request_id: str
             ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Decode-worker side: pull one sequence's KV. Returns (k, v, n_tokens)."""
    conn = transport.connect(host, port, request_id)
    try:
        header = json.loads(conn.recv_msg(max_len=1 << 16))
        if "error" in header:
            raise KeyError(f"prefill side: {header['error']}")
        k = _frombytes(conn.recv_msg(), header["dtype"], header["shape"])
        v = _frombytes(conn.recv_msg(), header["dtype"], header["shape"])
        conn.send_msg(b"OK")
        return k, v, header["n_tokens"]
    finally:
        conn.close()


class ICIHandoff:
    """Colocated prefill/decode engines on one slice: device-to-device copy.

    export_kv_device/import_kv operate on jax.Arrays; when both engines share
    devices XLA turns the gather+scatter into on-device copies (ICI for
    cross-chip shards) with no host bounce. The serving path reaches this
    via transfer.ici_registry when `--disaggregation-transfer-backend ici`
    finds the routed prefill engine in-process."""

    def __init__(self, prefill_engine, decode_engine):
        self.src = prefill_engine
        self.dst = decode_engine

    def transfer(self, req, first_token: int) -> None:
        k, v, _ = self.src.export_kv_device(req.request_id)
        self.dst.import_kv(req, first_token, k, v)
        self.src.release_parked(req.request_id)
