"""KV-cache handoff between prefill and decode workers.

Two data planes, selected by `--disaggregation-transfer-backend`
(mirroring /root/reference/examples/deploy/sglang/disagg.yaml:47-48):

- "ici": the handoff stays in device buffers. Two legs: (a) IN-PROCESS —
  colocated roles found via transfer.ici_registry move pages as jax.Arrays
  (XLA places a device-to-device copy; no host roundtrip); (b)
  CROSS-PROCESS — the prefill side stages the pages with a
  `jax.experimental.transfer` server (DeviceKVSource) and the decode side
  pulls them straight into its own device memory (DeviceKVClient). A pair
  that can do neither degrades to the TCP plane with a LOUD per-pair
  warning on the decode side.
- "dcn": cross-host — pages serialize to bytes and stream over the native
  transport (transfer.transport), with NIXL-style key rendezvous on the
  prefill worker's bootstrap port.

Wire schema (dcn): one message = JSON header (dtype/shape/n_tokens/first_token)
+ one message per tensor (k then v, raw bytes, C-order).
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Callable, Dict, Optional, Tuple

import numpy as np

from dynamo_tpu.transfer import transport

log = logging.getLogger("dynamo_tpu.kv_transfer")


def _tobytes(arr: np.ndarray) -> bytes:
    # bfloat16 has no numpy dtype string; ship raw bytes + jax dtype name
    return np.ascontiguousarray(arr).view(np.uint8).tobytes()


def _dtype_name(arr) -> str:
    return str(arr.dtype)


def _frombytes(data: bytes, dtype: str, shape) -> np.ndarray:
    if dtype == "bfloat16":
        import ml_dtypes

        np_dtype = np.dtype(ml_dtypes.bfloat16)
    else:
        np_dtype = np.dtype(dtype)
    return np.frombuffer(data, dtype=np_dtype).reshape(shape)


class KVSource:
    """Prefill-worker side: holds exported KV until the decode side pulls it.

    One accept thread serves the bootstrap port; each parked request is keyed
    by request_id. After a successful pull (or expiry) the engine's parked
    pages are released."""

    def __init__(self, engine, port: int = 0, parked_ttl_s: float = 120.0):
        self.engine = engine
        self.parked_ttl_s = parked_ttl_s
        self.listener = transport.Listener(port)
        self.port = self.listener.port
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="kv-source")
        self._thread.start()

    def close(self):
        self._stop = True
        self.listener.close()

    def _serve(self):
        last_expiry = 0.0
        while not self._stop:
            import time as _time

            now = _time.monotonic()
            if now - last_expiry > 10.0:
                # reclaim KV parked for peers that never pulled (crash / lost
                # ack) so failures can't bleed the page pool dry
                self.engine.expire_parked(self.parked_ttl_s)
                last_expiry = now
            try:
                conn, key = self.listener.accept(timeout_ms=500)
            except TimeoutError:
                continue
            except Exception:
                if self._stop:
                    return
                log.exception("kv-source accept failed")
                continue
            threading.Thread(
                target=self._handle, args=(conn, key), daemon=True
            ).start()

    def _handle(self, conn: transport.Connection, request_id: str):
        try:
            k, v, n_tokens = self.engine.export_kv(request_id)
            header = {
                "request_id": request_id,
                "n_tokens": n_tokens,
                "dtype": _dtype_name(k),
                "shape": list(k.shape),
            }
            conn.send_msg(json.dumps(header).encode())
            conn.send_msg(_tobytes(k))
            conn.send_msg(_tobytes(v))
            # wait for ack so pages outlive a mid-transfer failure
            ack = conn.recv_msg(max_len=64)
            if ack == b"OK":
                self.engine.release_parked(request_id)
        except KeyError:
            try:
                conn.send_msg(json.dumps({"error": "unknown request"}).encode())
            except Exception:
                pass
        except Exception:
            log.exception("kv transfer for %s failed", request_id)
        finally:
            conn.close()


def fetch_kv(host: str, port: int, request_id: str
             ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Decode-worker side: pull one sequence's KV. Returns (k, v, n_tokens)."""
    conn = transport.connect(host, port, request_id)
    try:
        header = json.loads(conn.recv_msg(max_len=1 << 16))
        if "error" in header:
            raise KeyError(f"prefill side: {header['error']}")
        k = _frombytes(conn.recv_msg(), header["dtype"], header["shape"])
        v = _frombytes(conn.recv_msg(), header["dtype"], header["shape"])
        conn.send_msg(b"OK")
        return k, v, header["n_tokens"]
    finally:
        conn.close()


# ----------------------------------------------------------- KVBM host tier --
# Cross-worker onboard (dynamo_tpu.kvbm): on a disagg or failover miss a
# worker pulls demoted prefix BLOCKS from a peer's host tier over this same
# TCP plane instead of re-prefilling them. One connection per pull; the key
# namespace ("kvbm") keeps it off the per-request parked-KV protocol above.

KVBM_KEY = "kvbm"


class HostTierSource:
    """Serves a worker's KVBM host-tier blocks to pulling peers.

    Wire: peer connects with key "kvbm", sends one JSON message
    {"blocks": [hex hash, ...]}; the source answers a JSON header
    {"found": n, "shape": [...], "dtype": "..."} for the longest
    consecutive-from-the-start run it holds, then n (k, v) raw-byte
    message pairs. Blocks are copied out of the pool under its lock, so
    concurrent demotes/LRU evictions can't tear a served block."""

    def __init__(self, kvbm, port: int = 0):
        self.kvbm = kvbm
        self.listener = transport.Listener(port)
        self.port = self.listener.port
        self._stop = False
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="kvbm-host-tier")
        self._thread.start()

    def close(self):
        self._stop = True
        self.listener.close()

    def _serve(self):
        while not self._stop:
            try:
                conn, key = self.listener.accept(timeout_ms=500)
            except TimeoutError:
                continue
            except Exception:
                if self._stop:
                    return
                log.exception("kvbm host-tier accept failed")
                continue
            if key != KVBM_KEY:
                conn.close()
                continue
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: transport.Connection):
        try:
            req = json.loads(conn.recv_msg(max_len=1 << 20))
            hashes = [bytes.fromhex(h) for h in req.get("blocks", [])]
            blocks = []
            for h in hashes:
                got = self.kvbm.pool.get(h)
                if got is None:
                    break
                blocks.append(got)
            header = {"found": len(blocks)}
            if blocks:
                header["shape"] = list(blocks[0][0].shape)
                header["dtype"] = _dtype_name(blocks[0][0])
            conn.send_msg(json.dumps(header).encode())
            for k, v in blocks:
                conn.send_msg(_tobytes(k))
                conn.send_msg(_tobytes(v))
        except Exception:
            log.exception("kvbm host-tier pull failed")
        finally:
            conn.close()


def fetch_host_blocks(host: str, port: int, hashes_hex
                      ) -> "list[Tuple[np.ndarray, np.ndarray]]":
    """Pull host-tier blocks from a peer. Returns the consecutive-from-the-
    start run the peer held, as (k, v) numpy pairs in host-pool layout."""
    conn = transport.connect(host, port, KVBM_KEY)
    try:
        conn.send_msg(json.dumps({"blocks": list(hashes_hex)}).encode())
        header = json.loads(conn.recv_msg(max_len=1 << 16))
        out = []
        for _ in range(int(header.get("found", 0))):
            k = _frombytes(conn.recv_msg(), header["dtype"], header["shape"])
            v = _frombytes(conn.recv_msg(), header["dtype"], header["shape"])
            out.append((k, v))
        return out
    finally:
        conn.close()


# ------------------------------------------------------- device-buffer plane --
# Cross-PROCESS leg of the "ici" backend: when prefill and decode engines
# are colocated on one slice but in different processes (the reference's
# standard disagg topology — separate pods,
# /root/reference/examples/deploy/sglang/disagg.yaml:47-52), the parked KV
# streams through `jax.experimental.transfer` — the decode side pulls the
# prefill side's device buffers directly (no np.asarray readback, no JSON
# byte pump). The in-process registry path remains the fastest leg; the TCP
# (dcn) plane remains the cross-slice fallback.


def _uuid64(key: str) -> int:
    """63-bit pull id. The decode side never derives this — it uses the
    `transfer_uuid` from the stage descriptor — so the key carries a
    per-stage nonce (see DeviceKVSource.stage)."""
    import hashlib

    return int.from_bytes(
        hashlib.sha256(key.encode()).digest()[:8], "big") >> 1


_XFER_LOCK = threading.Lock()
_XFER_SERVER = None


def _transfer_server():
    """Process-wide jax transfer server, started lazily.

    Lazy on purpose: (a) starting two servers in ONE process crashes the
    local bulk-transport factory (jaxlib streaming.cc CHECK), and in-process
    handoffs never need a server; (b) worker startup shouldn't pay the
    socket setup unless disagg device transfer is actually used.
    Bind host comes from DYNAMO_TPU_TRANSFER_BIND (default 0.0.0.0 — the
    advertised wildcard is substituted with the worker's URL host by the
    decode side)."""
    global _XFER_SERVER
    with _XFER_LOCK:
        if _XFER_SERVER is None:
            import os

            import jax
            from jax.experimental import transfer as jxfer

            bind = os.environ.get("DYNAMO_TPU_TRANSFER_BIND", "0.0.0.0")
            client = jax.devices()[0].client
            _XFER_SERVER = jxfer.start_transfer_server(
                client, f"{bind}:0", [f"{bind}:0"])
        return _XFER_SERVER


class DeviceKVSource:
    """Prefill side: stages a parked sequence's KV for a remote device pull.

    Staging is LAZY (the decode side's /disagg/stage RPC, not the prefill
    response): an eager await_pull would pin a gathered KV copy in device
    memory for every request whose peer then pulls over TCP instead — an
    HBM leak, since the transfer server has no un-await. Pages are released
    by the decode side's /disagg/release RPC (or the TTL sweep).

    Stage-then-crash peers are contained three ways:
    - outstanding stages are CAPPED (`max_staged`), counting BOTH live
      stages and expired-but-never-released ones: an un-pulled gather
      stays pinned in the transfer server (it has no un-await), so its
      slot is only freed by /disagg/release — making the cap a true hard
      bound on server-pinned HBM. Past the cap, stage() refuses and the
      peer degrades to the TCP plane.
    - a TTL sweep demotes expired entries to the leaked ledger (loudly),
      so operators see stage-then-crash peers in logs and /worker/stats;
      a late re-stage for a leaked request RESURRECTS the original
      coordinates instead of pinning a second gather.
    - each stage derives its pull uuid from a fresh NONCE, so a re-stage
      after release can never re-issue await_pull for a uuid the server
      has already seen (whose behavior is undefined — a jaxlib CHECK
      could kill the process rather than raise).
    A duplicate stage() for a request that is still staged returns the
    ORIGINAL coordinates instead of staging again (the peer retried the
    RPC or lost the response; the arrays are consumed by whichever pull
    lands first). The whole stage body runs under one lock: concurrent
    duplicate RPCs must not race past the ledger check and double-pin
    (the export gather is milliseconds; stage RPCs are per-request)."""

    def __init__(self, engine, staged_ttl_s: float = 120.0,
                 max_staged: int = 64):
        self.engine = engine
        self.staged_ttl_s = staged_ttl_s
        self.max_staged = max_staged
        self._warned = False
        self._lock = threading.Lock()
        # request_id -> (monotonic ts, descriptor dict, (k, v) array refs)
        self._staged: Dict[str, tuple] = {}
        # expired un-released stages: the transfer server still pins their
        # gathers, so they keep holding cap slots until /disagg/release
        self._leaked: Dict[str, tuple] = {}

    @property
    def eligible(self) -> bool:
        """Cheap pre-check advertised in the prefill response: v1 pulls
        single-device buffers, so a TP-sharded KV pool never stages (and
        never pays the export gather only to discard it)."""
        return len(self.engine.k_pages.sharding.device_set) == 1

    def counts(self) -> tuple:
        """(live, leaked) under ONE lock and sweep — a two-property read
        could sweep between them and count an expiring entry twice. The
        sweep on read keeps expiry observable in /worker/stats and
        /metrics even when no new stage traffic arrives."""
        import time as _time

        with self._lock:
            self._sweep_locked(_time.monotonic())
            return len(self._staged), len(self._leaked)

    @property
    def staged_count(self) -> int:
        return self.counts()[0]

    @property
    def leaked_count(self) -> int:
        """Expired un-released stages whose gathers the transfer server
        still pins (surfaced in /worker/stats for operators)."""
        return self.counts()[1]

    def _sweep_locked(self, now: float) -> None:
        dead = [rid for rid, (ts, _, _) in self._staged.items()
                if now - ts > self.staged_ttl_s]
        for rid in dead:
            self._leaked[rid] = self._staged.pop(rid)
        if dead:
            log.warning(
                "%d staged KV gather(s) expired un-pulled (%s): their "
                "device copies stay pinned in the transfer server (no "
                "un-await) and keep holding stage slots until "
                "/disagg/release", len(dead), ", ".join(dead[:5]))

    def mark_released(self, request_id: str) -> None:
        """Decode side released the request (post-pull): forget the stage."""
        with self._lock:
            self._staged.pop(request_id, None)
            self._leaked.pop(request_id, None)

    def stage(self, request_id: str) -> Optional[dict]:
        if not self.eligible:
            return None
        import secrets
        import time as _time

        now = _time.monotonic()
        with self._lock:
            self._sweep_locked(now)
            hit = self._staged.get(request_id)
            if hit is not None:
                return dict(hit[1])
            leaked = self._leaked.pop(request_id, None)
            if leaked is not None:
                # the peer came back after the TTL: its gather is still
                # pinned and pullable — resurrect rather than double-pin
                self._staged[request_id] = (now, leaked[1], leaked[2])
                return dict(leaked[1])
            if len(self._staged) + len(self._leaked) >= self.max_staged:
                log.warning(
                    "staged-KV cap reached (%d live + %d leaked); refusing "
                    "stage for %s — peer will use the TCP plane",
                    len(self._staged), len(self._leaked), request_id)
                return None
            k, v, _ = self.engine.export_kv_device(request_id)
            uid = _uuid64(f"{request_id}:{secrets.token_hex(8)}")
            try:
                srv = _transfer_server()
                srv.await_pull(uid, [k, v])
            except Exception as e:  # backend without transfer-server support
                if not self._warned:
                    self._warned = True
                    log.warning(
                        "device-buffer KV staging unavailable (%s); this "
                        "prefill worker will serve KV over the TCP plane", e)
                return None
            desc = {
                "transfer_address": srv.address(),
                "transfer_uuid": uid,
                "kv_shape": list(k.shape),
                "kv_dtype": str(k.dtype),
            }
            self._staged[request_id] = (now, desc, (k, v))
            return dict(desc)


class DeviceKVClient:
    """Decode side: pulls staged KV into local device memory."""

    def __init__(self):
        self._conns: Dict[str, object] = {}
        self._lock = threading.Lock()

    def pull(self, address: str, uuid: int, shape, dtype: str):
        import jax
        from jax.sharding import SingleDeviceSharding

        srv = _transfer_server()
        with self._lock:
            conn = self._conns.get(address)
            if conn is None:
                conn = srv.connect(address)
                self._conns[address] = conn
        sds = jax.ShapeDtypeStruct(
            tuple(shape), jnp_dtype(dtype),
            sharding=SingleDeviceSharding(jax.devices()[0]))
        k, v = conn.pull(uuid, [sds, sds])
        return k, v


def jnp_dtype(name: str):
    if name == "bfloat16":
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(name)


class ICIHandoff:
    """Colocated prefill/decode engines on one slice: device-to-device copy.

    export_kv_device/import_kv operate on jax.Arrays; when both engines share
    devices XLA turns the gather+scatter into on-device copies (ICI for
    cross-chip shards) with no host bounce. The serving path reaches this
    via transfer.ici_registry when `--disaggregation-transfer-backend ici`
    finds the routed prefill engine in-process."""

    def __init__(self, prefill_engine, decode_engine):
        self.src = prefill_engine
        self.dst = decode_engine

    def transfer(self, req, first_token: int) -> None:
        k, v, _ = self.src.export_kv_device(req.request_id)
        self.dst.import_kv(req, first_token, k, v)
        self.src.release_parked(req.request_id)
