"""Process-local engine registry for the ICI disagg data plane.

`--disaggregation-transfer-backend ici` (the reference's nixl slot,
/root/reference/examples/deploy/sglang/disagg.yaml:47-48) means the KV
handoff stays on-device: when the prefill engine a decode request was routed
to lives in THIS process (colocated roles on one slice — one pod hosting
both engines), the decode client skips the HTTP RPC + TCP byte pump entirely
and moves pages engine-to-engine as jax.Arrays, which XLA lowers to
device-to-device copies (ICI for cross-chip shards, no host bounce).

Workers register their engine under every URL they advertise; the decode
client consults the registry before falling back to the dcn (TCP) plane.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

_lock = threading.Lock()
_engines: Dict[str, object] = {}


def register(url: str, engine) -> None:
    with _lock:
        _engines[url.rstrip("/")] = engine


def unregister(url: str) -> None:
    with _lock:
        _engines.pop(url.rstrip("/"), None)


def lookup(url: str) -> Optional[object]:
    with _lock:
        return _engines.get(url.rstrip("/"))


def clear() -> None:
    with _lock:
        _engines.clear()
