"""vLLM-TPU-style engine backend alias (`python -m dynamo_tpu.vllm_tpu`), the
TPU counterpart of `python3 -m dynamo.vllm`
(/root/reference/examples/deploy/vllm/agg.yaml:29-35)."""
