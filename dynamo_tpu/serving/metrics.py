"""Prometheus metrics, stdlib-only.

The metric names ARE the compatibility contract: the reference's Grafana
dashboard queries these exact series
(/root/reference/examples/dgdr/trtllm/grafana-dynamo-dashboard-configmap.yaml:
121 requests_total, 214 time_to_first_token, 307 inter_token_latency,
400 request_duration, 493/504 input/output_sequence_tokens), so the dashboard
ports to this stack unchanged. Implemented in-process (counter/gauge/histogram
with _sum/_count/_bucket text exposition) to avoid a prometheus_client
dependency.

Exposition formats: the classic Prometheus text format
(`text/plain; version=0.0.4`) by default; when the scraper's Accept header
asks for `application/openmetrics-text`, histograms additionally emit their
stored trace **exemplars** in OpenMetrics exemplar syntax
(`name_bucket{le="..."} N # {trace_id="..."} value ts`) and the page ends
with `# EOF` — the bridge from a p99 latency bucket straight to its span
tree at `/debug/spans?trace_id=...` (docs/observability.md).

Labeled metrics declare their label names (`labelnames=("model",)`) so a
fresh scrape emits no phantom *unlabeled* zero sample for them; only truly
label-less metrics default to `name 0`.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0, 30.0, 60.0,
)
_TOKEN_BUCKETS = (1, 8, 32, 128, 512, 1024, 2048, 4096, 8192, 16384)

PROM_CONTENT_TYPE = "text/plain; version=0.0.4"
OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8")


def _escape_label_value(v) -> str:
    """Exposition-format label escaping: backslash first (or the other two
    escapes would be double-escaped), then quote and newline. Without this,
    one adversarial label value corrupts the whole /metrics scrape."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label_value(v)}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    def __init__(self, name: str, help_: str, registry: "Registry",
                 labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help_
        # declared label names: a labeled metric with no children yet emits
        # HELP/TYPE only — never a synthetic UNLABELED zero sample that
        # dashboards would read as a phantom series
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        registry._register(self)

    def _default_items(self):
        """The synthetic sample for an empty metric: `name 0` only when the
        metric is label-less by declaration."""
        return [] if self.labelnames else [((), 0.0)]


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_, registry, labelnames: Sequence[str] = ()):
        super().__init__(name, help_, registry, labelnames)
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def labels(self, **labels) -> "_CounterChild":
        return _CounterChild(self, tuple(sorted(labels.items())))

    def inc(self, amount: float = 1.0, **labels):
        self.labels(**labels).inc(amount)

    def values(self) -> Dict[Tuple[Tuple[str, str], ...], float]:
        """Point-in-time copy of every child's cumulative value (consumed by
        the SLO engine's delta bucketing, observability/slo.py)."""
        with self._lock:
            return dict(self._values)

    def expose(self, openmetrics: bool = False) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            items = list(self._values.items()) or self._default_items()
            for lbl, v in items:
                out.append(f"{self.name}{_fmt_labels(lbl)} {v}")
        return out


class _CounterChild:
    def __init__(self, parent: Counter, labels):
        self.parent, self.lbl = parent, labels

    def inc(self, amount: float = 1.0):
        with self.parent._lock:
            self.parent._values[self.lbl] = (
                self.parent._values.get(self.lbl, 0.0) + amount
            )


class CallbackCounter(_Metric):
    """Counter whose value is read from a callback at scrape time — for
    monotonic counts that live in another subsystem's own bookkeeping
    (e.g. the engine KVBM's block counters) without double-counting or
    cross-thread increment plumbing."""

    kind = "counter"

    def __init__(self, name, help_, registry, fn):
        super().__init__(name, help_, registry)
        self._fn = fn

    def expose(self, openmetrics: bool = False) -> List[str]:
        try:
            v = float(self._fn())
        except Exception:
            v = 0.0
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} counter", f"{self.name} {v}"]


class CallbackCounterVec(_Metric):
    """Labeled CallbackCounter: the callback returns a mapping from a
    label tuple (or dict) to a cumulative value, read at scrape time —
    for per-label-set counts kept in another subsystem's own bookkeeping
    (e.g. the attention dispatch's Pallas→XLA demotion counts by
    op/reason, ops/attention.pallas_fallback_counts)."""

    kind = "counter"

    def __init__(self, name, help_, registry, fn,
                 labelnames: Sequence[str] = ()):
        super().__init__(name, help_, registry, labelnames)
        self._fn = fn

    def expose(self, openmetrics: bool = False) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} counter"]
        try:
            items = self._fn() or {}
        except Exception:
            items = {}
        rows = []
        for lbl, v in items.items():
            if isinstance(lbl, dict):
                lbl = tuple(sorted(lbl.items()))
            rows.append((tuple(lbl), float(v)))
        for lbl, v in sorted(rows) or self._default_items():
            out.append(f"{self.name}{_fmt_labels(lbl)} {v}")
        return out


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_, registry, labelnames: Sequence[str] = ()):
        super().__init__(name, help_, registry, labelnames)
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def set(self, value: float, **labels):
        with self._lock:
            self._values[tuple(sorted(labels.items()))] = value

    def remove(self, **labels):
        """Drop one label-set's series (e.g. a device's stale variant after a
        label value flips) so it doesn't stay frozen at its last value."""
        with self._lock:
            self._values.pop(tuple(sorted(labels.items())), None)

    def expose(self, openmetrics: bool = False) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            items = list(self._values.items()) or self._default_items()
            for lbl, v in items:
                out.append(f"{self.name}{_fmt_labels(lbl)} {v}")
        return out


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_, registry,
                 buckets: Sequence[float] = _DEFAULT_BUCKETS,
                 labelnames: Sequence[str] = ()):
        super().__init__(name, help_, registry, labelnames)
        self.buckets = tuple(buckets)
        self._counts: Dict[Tuple[Tuple[str, str], ...], List[int]] = {}
        self._sum: Dict[Tuple[Tuple[str, str], ...], float] = {}
        self._n: Dict[Tuple[Tuple[str, str], ...], int] = {}
        # one exemplar per (label-set, bucket): the newest observation wins,
        # so a hot p99 bucket always links to a RECENT trace
        self._exemplars: Dict[Tuple[Tuple[Tuple[str, str], ...], int],
                              Tuple[str, float, float]] = {}

    def observe(self, value: float, exemplar: Optional[str] = None, **labels):
        """Record an observation; `exemplar` (a trace id) attaches to the
        bucket the value falls in and is emitted in OpenMetrics scrapes."""
        lbl = tuple(sorted(labels.items()))
        with self._lock:
            counts = self._counts.setdefault(lbl, [0] * (len(self.buckets) + 1))
            idx = len(self.buckets)  # +Inf unless a finite bucket matches
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    if i < idx:
                        idx = i
            counts[-1] += 1  # +Inf
            self._sum[lbl] = self._sum.get(lbl, 0.0) + value
            self._n[lbl] = self._n.get(lbl, 0) + 1
            if exemplar:
                self._exemplars[(lbl, idx)] = (
                    str(exemplar), float(value), time.time())

    def snapshot(self) -> Dict[Tuple[Tuple[str, str], ...],
                               Tuple[List[int], int, float]]:
        """Per-label-set (cumulative bucket counts, count, sum) copy — the
        SLO engine diffs consecutive snapshots into time buckets."""
        with self._lock:
            return {lbl: (list(c), self._n.get(lbl, 0),
                          self._sum.get(lbl, 0.0))
                    for lbl, c in self._counts.items()}

    def good_total(self, threshold: float
                   ) -> Dict[Tuple[Tuple[str, str], ...], Tuple[int, int]]:
        """Per-label-set (observations <= threshold, total observations).

        The threshold snaps DOWN to the largest bucket edge <= threshold
        (values between that edge and the threshold count as breaches —
        conservative). SLO targets should sit on bucket boundaries."""
        i = -1
        for j, b in enumerate(self.buckets):
            if b <= threshold:
                i = j
        out: Dict[Tuple[Tuple[str, str], ...], Tuple[int, int]] = {}
        with self._lock:
            for lbl, counts in self._counts.items():
                good = counts[i] if i >= 0 else 0
                out[lbl] = (good, self._n.get(lbl, 0))
        return out

    def _exemplar_suffix(self, lbl, idx) -> str:
        ex = self._exemplars.get((lbl, idx))
        if ex is None:
            return ""
        trace_id, value, ts = ex
        return (f' # {{trace_id="{_escape_label_value(trace_id)}"}} '
                f"{value} {round(ts, 3)}")

    def expose(self, openmetrics: bool = False) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            lbls = list(self._counts) or ([] if self.labelnames else [()])
            for lbl in lbls:
                counts = self._counts.get(lbl, [0] * (len(self.buckets) + 1))
                # note: pre-built le= pairs — a backslash escape inside an
                # f-string EXPRESSION is a SyntaxError before Python 3.12
                for i, b in enumerate(self.buckets):
                    le = f'le="{b}"'
                    line = (f"{self.name}_bucket{_fmt_labels(lbl, le)} "
                            f"{counts[i]}")
                    if openmetrics:
                        line += self._exemplar_suffix(lbl, i)
                    out.append(line)
                inf_le = 'le="+Inf"'
                line = f"{self.name}_bucket{_fmt_labels(lbl, inf_le)} {counts[-1]}"
                if openmetrics:
                    line += self._exemplar_suffix(lbl, len(self.buckets))
                out.append(line)
                out.append(
                    f"{self.name}_sum{_fmt_labels(lbl)} {self._sum.get(lbl, 0.0)}"
                )
                out.append(f"{self.name}_count{_fmt_labels(lbl)} {self._n.get(lbl, 0)}")
        return out


class CallbackHistogram(_Metric):
    """Histogram whose buckets are read from a callback at scrape time —
    the bridge that exposes the engine's in-loop PhaseTimer distributions
    (engine.EngineMetrics) as real Prometheus histograms without a second
    observation path in the hot loop.

    `fn()` returns an iterable of
    ``(labels_dict, edges_seconds, cumulative_counts, sum_seconds, count)``
    where ``cumulative_counts`` has ``len(edges) + 1`` entries (the last is
    +Inf and must equal ``count``)."""

    kind = "histogram"

    def __init__(self, name, help_, registry, fn):
        super().__init__(name, help_, registry)
        self._fn = fn

    def expose(self, openmetrics: bool = False) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} histogram"]
        try:
            series = list(self._fn())
        except Exception:
            series = []
        for labels, edges, cum, sum_s, count in series:
            lbl = tuple(sorted(labels.items()))
            for i, edge in enumerate(edges):
                le = f'le="{edge}"'
                out.append(f"{self.name}_bucket{_fmt_labels(lbl, le)} {cum[i]}")
            inf_le = 'le="+Inf"'
            out.append(f"{self.name}_bucket{_fmt_labels(lbl, inf_le)} "
                       f"{cum[len(edges)]}")
            out.append(f"{self.name}_sum{_fmt_labels(lbl)} {sum_s}")
            out.append(f"{self.name}_count{_fmt_labels(lbl)} {count}")
        return out


class Registry:
    def __init__(self):
        self._metrics: List[_Metric] = []
        self._lock = threading.Lock()

    def _register(self, m: _Metric):
        with self._lock:
            self._metrics.append(m)

    def expose(self, openmetrics: bool = False) -> str:
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            lines.extend(m.expose(openmetrics=openmetrics))
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def scrape(self, accept: Optional[str]) -> Tuple[bytes, str]:
        """Content negotiation for a /metrics handler: OpenMetrics (with
        exemplars) when the scraper asks for it, classic text otherwise."""
        om = bool(accept and "application/openmetrics-text" in accept)
        body = self.expose(openmetrics=om).encode()
        return body, (OPENMETRICS_CONTENT_TYPE if om else PROM_CONTENT_TYPE)


class FrontendMetrics:
    """The dynamo_frontend_* serving-metric contract (SURVEY.md §5)."""

    def __init__(self, registry: Optional[Registry] = None):
        self.registry = registry or Registry()
        r = self.registry
        self.requests_total = Counter(
            "dynamo_frontend_requests_total", "Total LLM requests", r,
            labelnames=("model",),
        )
        self.errors_total = Counter(
            "dynamo_frontend_errors_total",
            "Requests answered with a 5xx by this process (the error-rate "
            "SLO source, observability/slo.py)", r,
            labelnames=("model", "code"),
        )
        self.ttft = Histogram(
            "dynamo_frontend_time_to_first_token_seconds",
            "Time to first token", r, labelnames=("model",),
        )
        self.itl = Histogram(
            "dynamo_frontend_inter_token_latency_seconds",
            "Inter-token latency", r, labelnames=("model",),
        )
        self.duration = Histogram(
            "dynamo_frontend_request_duration_seconds",
            "End-to-end request duration", r, labelnames=("model",),
        )
        self.isl = Histogram(
            "dynamo_frontend_input_sequence_tokens",
            "Input sequence length (tokens)", r, buckets=_TOKEN_BUCKETS,
            labelnames=("model",),
        )
        self.osl = Histogram(
            "dynamo_frontend_output_sequence_tokens",
            "Output sequence length (tokens)", r, buckets=_TOKEN_BUCKETS,
            labelnames=("model",),
        )
        self.queued = Gauge(
            "dynamo_frontend_queued_requests", "Requests queued or in flight", r
        )
        # --- per-tenant QoS (dynamo_tpu.qos; docs/robustness.md) ---
        # tenant-labeled latency series: the per-tenant SLO selectors
        # (observability/slo.py SLOTarget.tenant) and the QoS isolation
        # acceptance tests read THESE, so an aggressive tenant's tail
        # can't hide inside the model-labeled aggregate. Labelnames are
        # declared, so an untenanted deployment emits no phantom samples.
        self.tenant_requests = Counter(
            "dynamo_tenant_requests_total",
            "Requests by resolved tenant identity", r,
            labelnames=("tenant",),
        )
        self.tenant_ttft = Histogram(
            "dynamo_tenant_time_to_first_token_seconds",
            "Time to first token by tenant", r, labelnames=("tenant",),
        )
        self.tenant_itl = Histogram(
            "dynamo_tenant_inter_token_latency_seconds",
            "Inter-token latency by tenant", r, labelnames=("tenant",),
        )
