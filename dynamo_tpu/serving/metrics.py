"""Prometheus metrics, stdlib-only.

The metric names ARE the compatibility contract: the reference's Grafana
dashboard queries these exact series
(/root/reference/examples/dgdr/trtllm/grafana-dynamo-dashboard-configmap.yaml:
121 requests_total, 214 time_to_first_token, 307 inter_token_latency,
400 request_duration, 493/504 input/output_sequence_tokens), so the dashboard
ports to this stack unchanged. Implemented in-process (counter/gauge/histogram
with _sum/_count/_bucket text exposition) to avoid a prometheus_client
dependency.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

_DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
    5.0, 10.0, 30.0, 60.0,
)
_TOKEN_BUCKETS = (1, 8, 32, 128, 512, 1024, 2048, 4096, 8192, 16384)


def _escape_label_value(v) -> str:
    """Exposition-format label escaping: backslash first (or the other two
    escapes would be double-escaped), then quote and newline. Without this,
    one adversarial label value corrupts the whole /metrics scrape."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels: Tuple[Tuple[str, str], ...], extra: str = "") -> str:
    parts = [f'{k}="{_escape_label_value(v)}"' for k, v in labels]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    def __init__(self, name: str, help_: str, registry: "Registry"):
        self.name = name
        self.help = help_
        self._lock = threading.Lock()
        registry._register(self)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_, registry):
        super().__init__(name, help_, registry)
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def labels(self, **labels) -> "_CounterChild":
        return _CounterChild(self, tuple(sorted(labels.items())))

    def inc(self, amount: float = 1.0, **labels):
        self.labels(**labels).inc(amount)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        with self._lock:
            items = list(self._values.items()) or [((), 0.0)]
            for lbl, v in items:
                out.append(f"{self.name}{_fmt_labels(lbl)} {v}")
        return out


class _CounterChild:
    def __init__(self, parent: Counter, labels):
        self.parent, self.lbl = parent, labels

    def inc(self, amount: float = 1.0):
        with self.parent._lock:
            self.parent._values[self.lbl] = (
                self.parent._values.get(self.lbl, 0.0) + amount
            )


class CallbackCounter(_Metric):
    """Counter whose value is read from a callback at scrape time — for
    monotonic counts that live in another subsystem's own bookkeeping
    (e.g. the engine KVBM's block counters) without double-counting or
    cross-thread increment plumbing."""

    kind = "counter"

    def __init__(self, name, help_, registry, fn):
        super().__init__(name, help_, registry)
        self._fn = fn

    def expose(self) -> List[str]:
        try:
            v = float(self._fn())
        except Exception:
            v = 0.0
        return [f"# HELP {self.name} {self.help}",
                f"# TYPE {self.name} counter", f"{self.name} {v}"]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_, registry):
        super().__init__(name, help_, registry)
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    def set(self, value: float, **labels):
        with self._lock:
            self._values[tuple(sorted(labels.items()))] = value

    def remove(self, **labels):
        """Drop one label-set's series (e.g. a device's stale variant after a
        label value flips) so it doesn't stay frozen at its last value."""
        with self._lock:
            self._values.pop(tuple(sorted(labels.items())), None)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        with self._lock:
            items = list(self._values.items()) or [((), 0.0)]
            for lbl, v in items:
                out.append(f"{self.name}{_fmt_labels(lbl)} {v}")
        return out


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_, registry, buckets: Sequence[float] = _DEFAULT_BUCKETS):
        super().__init__(name, help_, registry)
        self.buckets = tuple(buckets)
        self._counts: Dict[Tuple[Tuple[str, str], ...], List[int]] = {}
        self._sum: Dict[Tuple[Tuple[str, str], ...], float] = {}
        self._n: Dict[Tuple[Tuple[str, str], ...], int] = {}

    def observe(self, value: float, **labels):
        lbl = tuple(sorted(labels.items()))
        with self._lock:
            counts = self._counts.setdefault(lbl, [0] * (len(self.buckets) + 1))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            counts[-1] += 1  # +Inf
            self._sum[lbl] = self._sum.get(lbl, 0.0) + value
            self._n[lbl] = self._n.get(lbl, 0) + 1

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        with self._lock:
            lbls = list(self._counts) or [()]
            for lbl in lbls:
                counts = self._counts.get(lbl, [0] * (len(self.buckets) + 1))
                # note: pre-built le= pairs — a backslash escape inside an
                # f-string EXPRESSION is a SyntaxError before Python 3.12
                for i, b in enumerate(self.buckets):
                    le = f'le="{b}"'
                    out.append(
                        f"{self.name}_bucket{_fmt_labels(lbl, le)} "
                        f"{counts[i]}"
                    )
                inf_le = 'le="+Inf"'
                out.append(
                    f"{self.name}_bucket{_fmt_labels(lbl, inf_le)} {counts[-1]}"
                )
                out.append(
                    f"{self.name}_sum{_fmt_labels(lbl)} {self._sum.get(lbl, 0.0)}"
                )
                out.append(f"{self.name}_count{_fmt_labels(lbl)} {self._n.get(lbl, 0)}")
        return out


class Registry:
    def __init__(self):
        self._metrics: List[_Metric] = []
        self._lock = threading.Lock()

    def _register(self, m: _Metric):
        with self._lock:
            self._metrics.append(m)

    def expose(self) -> str:
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


class FrontendMetrics:
    """The dynamo_frontend_* serving-metric contract (SURVEY.md §5)."""

    def __init__(self, registry: Optional[Registry] = None):
        self.registry = registry or Registry()
        r = self.registry
        self.requests_total = Counter(
            "dynamo_frontend_requests_total", "Total LLM requests", r
        )
        self.ttft = Histogram(
            "dynamo_frontend_time_to_first_token_seconds",
            "Time to first token", r,
        )
        self.itl = Histogram(
            "dynamo_frontend_inter_token_latency_seconds",
            "Inter-token latency", r,
        )
        self.duration = Histogram(
            "dynamo_frontend_request_duration_seconds",
            "End-to-end request duration", r,
        )
        self.isl = Histogram(
            "dynamo_frontend_input_sequence_tokens",
            "Input sequence length (tokens)", r, buckets=_TOKEN_BUCKETS,
        )
        self.osl = Histogram(
            "dynamo_frontend_output_sequence_tokens",
            "Output sequence length (tokens)", r, buckets=_TOKEN_BUCKETS,
        )
        self.queued = Gauge(
            "dynamo_frontend_queued_requests", "Requests queued or in flight", r
        )
