"""Background scheduler thread bridging the synchronous Engine to concurrent
HTTP handlers via per-request event queues.

This is the in-process analogue of the reference's worker runtime loop: HTTP
threads enqueue GenRequests; one scheduler thread drives Engine.step() and
fans TokenEvents out to stream queues.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Dict, Iterator, Optional

from dynamo_tpu.engine.engine import Engine
from dynamo_tpu.engine.request import GenRequest, TokenEvent
from dynamo_tpu.robustness import deadline as ddl
from dynamo_tpu.robustness import faults

log = logging.getLogger("dynamo_tpu.service")


class EngineService:
    def __init__(self, engine: Engine):
        self.engine = engine
        self._queues: Dict[str, "queue.Queue[TokenEvent]"] = {}
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = False
        # resurrection (watchdog escalation thread) tears streams down via
        # engine.abort_all — flush their queues so waiting handlers see a
        # final event instead of polling a dead request forever. Set on
        # the RAW engine: a ReplicatedEngine wrapper proxies reads, not
        # writes, and abort_all runs on the inner object
        getattr(engine, "engine", engine).on_abort_all = self._flush_aborted
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="engine-scheduler")
        self._thread.start()

    # ------------------------------------------------------------- lifecycle
    def close(self):
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=10)

    # --------------------------------------------------------------- intake
    def submit(self, req: GenRequest) -> "queue.Queue[TokenEvent]":
        """Validate and enqueue; raises ValueError BEFORE any output starts,
        so HTTP handlers can reject with a clean status line."""
        faults.sleep_point("worker.slow_prefill")
        q: "queue.Queue[TokenEvent]" = queue.Queue()
        with self._lock:
            self._queues[req.request_id] = q
        try:
            self.engine.add_request(req)
        except ValueError:
            with self._lock:
                self._queues.pop(req.request_id, None)
            raise
        self._wake.set()
        return q

    def abort(self, request_id: str):
        self.engine.abort_request(request_id)
        self._wake.set()

    def attach(self, request_id: str) -> "queue.Queue[TokenEvent]":
        """Register an event queue for a request that enters the engine via a
        side door (disagg KV import) rather than add_request()."""
        q: "queue.Queue[TokenEvent]" = queue.Queue()
        with self._lock:
            self._queues[request_id] = q
        return q

    def detach(self, request_id: str):
        with self._lock:
            self._queues.pop(request_id, None)

    def wake(self):
        self._wake.set()

    def nudge_all(self) -> None:
        """Push a synthetic no-op event to every open stream queue.  A
        wedged engine emits nothing, so handles blocked in drain() would
        never observe a drain-handoff signal; the nudge wakes them (the
        handoff branch runs before token processing, and token_id=-1 with
        finished=False is ignored everywhere else)."""
        with self._lock:
            for rid, q in list(self._queues.items()):
                q.put(TokenEvent(rid, -1, 0, False, None))

    def _flush_aborted(self, ids) -> None:
        """engine.on_abort_all hook: terminate the stream queues of every
        torn-down request (idempotent — a queue already popped by the
        fatal-step path is simply absent)."""
        with self._lock:
            for rid in ids:
                q = self._queues.pop(rid, None)
                if q is not None:
                    q.put(TokenEvent(rid, -1, 0, True, "abort"))

    def sampling_state(self, request_id: str):
        """Resumable sampling-state export (engine.export_sampling_state):
        the drain-handoff path journals this so a continuation on another
        worker resumes the exact PRNG chain. None once the request left
        the engine."""
        return self.engine.export_sampling_state(request_id)

    def stream(self, req: GenRequest,
               timeout: Optional[float] = None) -> Iterator[TokenEvent]:
        """Submit and yield TokenEvents until the request finishes."""
        q = self.submit(req)
        return self.drain(req, q, timeout)

    def drain(self, req: GenRequest, q: "queue.Queue[TokenEvent]",
              timeout: Optional[float] = None) -> Iterator[TokenEvent]:
        """Yield TokenEvents for an already-submitted request.

        `timeout` is the request's remaining deadline budget (propagated
        from the client's x-deadline header); None falls back to the
        operator's DYNAMO_TPU_DEADLINE_S default — the former hard-coded
        600 s."""
        if timeout is None:
            timeout = ddl.default_budget_s()
        deadline = time.monotonic() + timeout
        try:
            while True:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.abort(req.request_id)
                    raise TimeoutError(
                        f"request {req.request_id} exceeded its "
                        f"{timeout:.1f}s deadline budget")
                try:
                    # short poll so a server shutdown can't strand the handler;
                    # a slow first token (jit compile) just keeps polling until
                    # the overall deadline
                    ev = q.get(timeout=min(remaining, 5.0))
                except queue.Empty:
                    continue
                yield ev
                if ev.finished:
                    return
                if faults.check("worker.crash_mid_decode") is not None:
                    # the worker "crashes" with tokens already delivered:
                    # abort the engine side and die mid-stream — the
                    # frontend either resumes the journaled continuation
                    # on another worker (recovery plane) or truncates;
                    # it never re-runs the whole generation
                    self.abort(req.request_id)
                    raise ConnectionResetError(
                        "injected fault: worker.crash_mid_decode")
        finally:
            with self._lock:
                self._queues.pop(req.request_id, None)

    # ------------------------------------------------------------ scheduler
    def _run(self):
        idle_tick = getattr(self.engine, "idle_tick", None)
        while not self._stop:
            if not self.engine.has_work:
                if idle_tick is not None:
                    # multi-host leader: heartbeat the replication plane so
                    # idle followers' pending collective never times out
                    idle_tick()
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            try:
                events = self.engine.step()
            except Exception as e:
                log.exception("engine step failed; aborting in-flight requests")
                flight = getattr(self.engine, "flight", None)
                if flight is not None:
                    # name the failure before abort_all() dumps the ring —
                    # the dump tail then ends with [fatal_step, dump]
                    flight.note("fatal_step", error=repr(e))
                watchdog = getattr(self.engine, "watchdog", None)
                if watchdog is not None:
                    # health state machine: suspect -> in-place
                    # resurrection (this thread is NOT wedged — it caught
                    # the error), or permanent quarantine on repeat trips.
                    # Resurrection's abort_all flushes our queues via the
                    # on_abort_all hook, so every waiter sees a final
                    # event and the worker's advertised health changes
                    # BEFORE it takes new work.
                    watchdog.on_fatal_step(e)
                else:
                    ids = self.engine.abort_all()
                    with self._lock:
                        for rid in ids:
                            q = self._queues.pop(rid, None)
                            if q is not None:
                                q.put(TokenEvent(rid, -1, 0, True, "abort"))
                time.sleep(0.5)
                continue
            if events:
                with self._lock:
                    for ev in events:
                        q = self._queues.get(ev.request_id)
                        if q is not None:
                            q.put(ev)
