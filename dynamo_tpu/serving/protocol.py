"""OpenAI-compatible request/response shaping (dict-level, stdlib-only).

API surface contract: /v1/models and /v1/chat/completions (+ /v1/completions)
exactly as the reference exposes them (/root/reference/README.md:277-292,
/root/reference/deploy-incluster.sh:497-501), including SSE streaming chunks.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional, Tuple


class BadRequest(Exception):
    pass


def new_id(prefix: str = "chatcmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex[:24]}"


MAX_N = 8  # choices per request; bounded so one request can't hog the batch
MAX_TOP_LOGPROBS = 5  # engine computes top-5 alternatives per step
# request `priority` bounds (vLLM semantics: lower admits sooner). Bounded
# so a client's raw JSON can never dominate the engine's preemption-victim
# ranking — the tenant QoS plane reserves the space above this range for
# its over-budget penalty (dynamo_tpu.qos.tenancy.OVER_BUDGET_PENALTY).
PRIORITY_MIN, PRIORITY_MAX = -100, 100


def _common_sampling(body: Dict[str, Any]) -> Dict[str, Any]:
    """Fields shared by chat + completions: sampling, penalties, seed, stop,
    n, stream/stream_options."""
    temperature = _num(body, "temperature", 1.0)
    if temperature < 0:
        raise BadRequest("'temperature' must be >= 0")
    for key in ("presence_penalty", "frequency_penalty"):
        v = _num(body, key, 0.0)
        if not -2.0 <= v <= 2.0:
            raise BadRequest(f"'{key}' must be in [-2, 2]")
    seed = body.get("seed")
    if seed is not None and (isinstance(seed, bool) or not isinstance(seed, int)):
        raise BadRequest("'seed' must be an integer")
    n = body.get("n", 1)
    if isinstance(n, bool) or not isinstance(n, int) or not 1 <= n <= MAX_N:
        raise BadRequest(f"'n' must be an integer in [1, {MAX_N}]")
    priority = body.get("priority", 0)
    if isinstance(priority, bool) or not isinstance(priority, int) \
            or not PRIORITY_MIN <= priority <= PRIORITY_MAX:
        raise BadRequest(
            f"'priority' must be an integer in "
            f"[{PRIORITY_MIN}, {PRIORITY_MAX}]")
    min_p = _num(body, "min_p", 0.0)
    if not 0.0 <= min_p < 1.0:
        raise BadRequest("'min_p' must be in [0, 1)")
    return {
        "temperature": temperature,
        "top_p": _num(body, "top_p", 1.0),
        "top_k": int(_num(body, "top_k", 0)),
        "presence_penalty": _num(body, "presence_penalty", 0.0),
        "frequency_penalty": _num(body, "frequency_penalty", 0.0),
        "min_p": min_p,
        "logit_bias": _parse_logit_bias(body),
        "seed": seed,
        "n": n,
        # admission-priority extension (vLLM semantics: lower = sooner)
        "priority": priority,
        "stop": _parse_stop(body),
        "stop_token_ids": _parse_stop_token_ids(body),
        "stream": bool(body.get("stream", False)),
        "include_usage": _include_usage(body),
        "ignore_eos": bool(body.get("ignore_eos", False)),
    }


def _parse_stop_token_ids(body: Dict[str, Any]) -> List[int]:
    """vLLM extension: stop on exact token ids (no detokenize round trip);
    model EOS ids still stop generation as usual."""
    ids = body.get("stop_token_ids")
    if ids is None:
        return []
    if (not isinstance(ids, list) or len(ids) > 16
            or not all(isinstance(i, int) and not isinstance(i, bool)
                       and i >= 0 for i in ids)):
        raise BadRequest(
            "'stop_token_ids' must be up to 16 non-negative integers")
    return ids


def _parse_logit_bias(body: Dict[str, Any]):
    """OpenAI logit_bias: {"<token_id>": bias in [-100, 100]}. The engine
    packs at most BIAS_K entries into fixed lanes — reject larger maps
    rather than silently dropping biases. {} is a no-op, per OpenAI."""
    from dynamo_tpu.engine.request import BIAS_K

    lb = body.get("logit_bias")
    if lb is None or lb == {}:
        return None
    if not isinstance(lb, dict):
        raise BadRequest("'logit_bias' must be an object")
    if len(lb) > BIAS_K:
        raise BadRequest(
            f"'logit_bias' supports at most {BIAS_K} entries")
    out = {}
    for k, v in lb.items():
        try:
            tok = int(k)
        except (TypeError, ValueError):
            raise BadRequest("'logit_bias' keys must be token ids")
        if isinstance(v, bool) or not isinstance(v, (int, float)) \
                or not -100.0 <= float(v) <= 100.0:
            raise BadRequest("'logit_bias' values must be in [-100, 100]")
        if tok < 0:
            raise BadRequest("'logit_bias' token ids must be >= 0")
        out[tok] = float(v)
    return out


def _parse_stop(body: Dict[str, Any]) -> List[str]:
    stop = body.get("stop")
    if stop is None:
        return []
    if isinstance(stop, str):
        stop = [stop]
    if (not isinstance(stop, list) or len(stop) > 4
            or not all(isinstance(s, str) and s for s in stop)):
        raise BadRequest(
            "'stop' must be a non-empty string or up to 4 non-empty strings"
        )
    return stop


def parse_chat_request(body: Dict[str, Any]) -> Dict[str, Any]:
    if not isinstance(body, dict):
        raise BadRequest("body must be a JSON object")
    messages = body.get("messages")
    if not isinstance(messages, list) or not messages:
        raise BadRequest("'messages' must be a non-empty array")
    for m in messages:
        if not isinstance(m, dict) or "role" not in m:
            raise BadRequest("each message needs 'role'")
        # content is optional exactly when the assistant turn carries
        # tool_calls (OpenAI multi-turn tool conversations)
        if "content" not in m and not m.get("tool_calls"):
            raise BadRequest("each message needs 'content' (or "
                             "'tool_calls' on assistant turns)")
    model = body.get("model")
    if not isinstance(model, str) or not model:
        raise BadRequest("'model' is required")
    # max_completion_tokens is the current OpenAI name; max_tokens the legacy
    # alias — accept both; explicit null means absent (OpenAI semantics)
    mt = body.get("max_tokens")
    if mt is None:
        mt = body.get("max_completion_tokens")
    if mt is None:
        mt = 512
    if isinstance(mt, bool) or not isinstance(mt, int) or mt < 1:
        raise BadRequest("'max_tokens' must be a positive integer")
    lp = body.get("logprobs", False)
    if not isinstance(lp, bool):
        raise BadRequest("'logprobs' must be a boolean for chat completions")
    top_lp = body.get("top_logprobs", 0)
    if (isinstance(top_lp, bool) or not isinstance(top_lp, int)
            or not 0 <= top_lp <= MAX_TOP_LOGPROBS):
        raise BadRequest(
            f"'top_logprobs' must be an integer in [0, {MAX_TOP_LOGPROBS}]"
        )
    if top_lp and not lp:
        raise BadRequest("'top_logprobs' requires 'logprobs': true")
    tools, tool_choice = _parse_tools(body)
    return {
        "model": model,
        "messages": messages,
        "max_tokens": mt,
        # engine logprobs: None = off; N = chosen + top-N alternatives
        "logprobs": top_lp if lp else None,
        "guided_json": _parse_response_format(body),
        "tools": tools,
        "tool_choice": tool_choice,
        **_common_sampling(body),
    }


def _parse_tools(body: Dict[str, Any]):
    """OpenAI `tools` + `tool_choice`. Returns (tools, tool_choice) where
    tool_choice is "none", "auto", or the tagged tuple
    ("function", name) for a forced function (tagged so a tool literally
    named "auto"/"none" can still be forced).

    A forced function rides the JSON-guided decoder: the completion is
    constrained to one JSON object, returned as the call's arguments.
    "auto" serves text and surfaces a tool call only when the model emits
    the canonical {"name": ..., "arguments": {...}} object (the reference
    stack's engines likewise need a model-specific parser for free-form
    tool syntax)."""
    tools = body.get("tools")
    if tools is None:
        if body.get("tool_choice") not in (None, "none"):
            raise BadRequest("'tool_choice' requires 'tools'")
        return None, "none"
    if not isinstance(tools, list) or not tools:
        raise BadRequest("'tools' must be a non-empty array")
    names = []
    for t in tools:
        fn = t.get("function") if isinstance(t, dict) else None
        if (not isinstance(t, dict) or t.get("type") != "function"
                or not isinstance(fn, dict)
                or not isinstance(fn.get("name"), str)):
            raise BadRequest(
                "each tool must be {'type': 'function', 'function': "
                "{'name': ..., ...}}")
        names.append(fn["name"])
    tc = body.get("tool_choice")
    if tc is None:  # explicit null == absent (OpenAI default)
        tc = "auto"
    if tc in ("auto", "none"):
        return tools, tc
    if (isinstance(tc, dict) and tc.get("type") == "function"
            and isinstance(tc.get("function"), dict)):
        name = tc["function"].get("name")
        if name not in names:
            raise BadRequest(f"tool_choice names unknown function {name!r}")
        # tagged so a tool literally named "auto"/"none" can be forced
        return tools, ("function", name)
    raise BadRequest(
        "'tool_choice' must be 'auto', 'none', or "
        "{'type': 'function', 'function': {'name': ...}}")


class AutoToolStreamGate:
    """Streaming gate for tool_choice "auto": decide per choice whether
    the stream is a tool call without giving up streaming for plain text.

    The only auto shape this stack surfaces is the canonical
    {"name", "arguments"} object, which must START with '{' — so the
    gate probes the first non-whitespace character: anything else flushes
    the held text (verbatim, leading whitespace included) and streams
    normally from then on; a '{' buffers the whole choice and, at
    finish, either emits one tool_calls delta (the text parsed as a
    canonical call) or flushes the buffered text. Logprob entries ride
    WITH their text: held entries are released on flush so token/logprob
    alignment survives, and dropped only when the text itself becomes a
    tool call (content is null there).

    feed(delta, lp_entry) -> (text to emit now, lp entries to emit now).
    finish(tools, tool_choice) -> (tool_call | None, leftover_text,
    leftover lp entries)."""

    def __init__(self):
        self._mode = "probe"  # probe -> buffer | stream
        self._parts: List[str] = []
        self._lp: List[Dict] = []

    def feed(self, delta: str, lp_entry: Optional[Dict] = None):
        if self._mode == "stream":
            return delta, ([lp_entry] if lp_entry is not None else [])
        self._parts.append(delta)
        if lp_entry is not None:
            self._lp.append(lp_entry)
        if self._mode == "probe":
            stripped = "".join(self._parts).lstrip()
            if stripped:
                if stripped[0] == "{":
                    self._mode = "buffer"
                else:
                    self._mode = "stream"
                    held, entries = "".join(self._parts), self._lp
                    self._parts, self._lp = [], []
                    return held, entries
        return "", []

    def finish(self, tools, tool_choice):
        held, entries = "".join(self._parts), self._lp
        self._parts, self._lp = [], []
        if self._mode != "buffer":
            self._mode = "stream"
            return None, held, entries  # whitespace-only probe flushes too
        self._mode = "stream"
        call = extract_tool_call(held, tools, tool_choice)
        if call is not None:
            return call, "", []  # content is null: entries describe nothing
        return None, held, entries


def tool_call_chunk_delta(call: Dict[str, Any]) -> Dict[str, Any]:
    """delta payload carrying a complete streamed tool call (index 0)."""
    return {"tool_calls": [{"index": 0, **call}]}


def extract_tool_call(text: str, tools, tool_choice):
    """Map generated text to an OpenAI tool_calls entry, or None.

    Forced choice (("function", name) tag): the guided decoder produced
    one JSON object — it IS the arguments, re-validated here so a
    stop-string truncation can never ship unparseable arguments under
    the grammar guarantee. Auto: accept only the canonical
    {"name": <known tool>, "arguments": <object>} shape."""
    import json as _json

    if tool_choice == "none" or not tools:
        return None
    if isinstance(tool_choice, tuple):  # ("function", name)
        try:
            if not isinstance(_json.loads(text), dict):
                return None
        except Exception:
            return None
        return {"id": new_id("call"), "type": "function",
                "function": {"name": tool_choice[1], "arguments": text}}
    try:
        obj = _json.loads(text)
    except Exception:
        return None
    if not isinstance(obj, dict) or set(obj) != {"name", "arguments"}:
        return None
    known = {t["function"]["name"] for t in tools}
    if obj["name"] not in known:
        return None
    args = obj["arguments"]
    if isinstance(args, str):
        # string arguments must themselves parse to an object, or a
        # client's json.loads(arguments) would crash on our output
        try:
            if not isinstance(_json.loads(args), dict):
                return None
        except Exception:
            return None
    elif not isinstance(args, dict):
        return None  # scalar arguments are not a canonical call
    return {"id": new_id("call"), "type": "function",
            "function": {"name": obj["name"],
                         "arguments": (args if isinstance(args, str)
                                       else _json.dumps(args))}}


def _parse_response_format(body: Dict[str, Any]) -> bool:
    """OpenAI response_format: {"type": "json_object"} constrains the
    completion to one JSON object (device-side grammar —
    ops/json_guide.py); "text"/absent is unconstrained; "json_schema" is
    explicitly unsupported (schema-level constraints are not wired)."""
    rf = body.get("response_format")
    if rf is None:
        return False
    if not isinstance(rf, dict) or "type" not in rf:
        raise BadRequest("'response_format' must be an object with 'type'")
    kind = rf["type"]
    if kind == "text":
        return False
    if kind == "json_object":
        return True
    if kind == "json_schema":
        raise BadRequest(
            "response_format type 'json_schema' is not supported; use "
            "'json_object'")
    raise BadRequest(f"unknown response_format type {kind!r}")


def _include_usage(body: Dict[str, Any]) -> bool:
    so_raw = body.get("stream_options")
    if so_raw is None:
        return False
    if not isinstance(so_raw, dict):
        raise BadRequest("'stream_options' must be an object")
    if not body.get("stream", False):
        # OpenAI returns 400 for stream_options without stream=true
        raise BadRequest("'stream_options' requires 'stream': true")
    return bool(so_raw.get("include_usage", False))


def _usage(prompt_tokens: int, completion_tokens: int) -> Dict[str, int]:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


def _num(body: Dict[str, Any], key: str, default: float) -> float:
    v = body.get(key, default)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise BadRequest(f"'{key}' must be a number")
    return float(v)


def parse_completion_request(body: Dict[str, Any]) -> Dict[str, Any]:
    if not isinstance(body, dict):
        raise BadRequest("body must be a JSON object")
    prompt = body.get("prompt")
    if isinstance(prompt, list):
        if not prompt or not all(isinstance(p, str) for p in prompt):
            raise BadRequest("'prompt' array must contain strings")
        prompt = prompt[0]
    if not isinstance(prompt, str):
        raise BadRequest("'prompt' must be a string")
    model = body.get("model")
    if not isinstance(model, str) or not model:
        raise BadRequest("'model' is required")
    mt = body.get("max_tokens", 16)
    if isinstance(mt, bool) or not isinstance(mt, int) or mt < 1:
        raise BadRequest("'max_tokens' must be a positive integer")
    # legacy completions logprobs: an integer count of alternatives
    lp = body.get("logprobs")
    if lp is not None and (
        isinstance(lp, bool) or not isinstance(lp, int)
        or not 0 <= lp <= MAX_TOP_LOGPROBS
    ):
        raise BadRequest(
            f"'logprobs' must be an integer in [0, {MAX_TOP_LOGPROBS}]"
        )
    return {
        "model": model,
        "prompt": prompt,
        "max_tokens": mt,
        "logprobs": lp,
        # vLLM's OpenAI server accepts response_format on completions
        # too; same device-side grammar as chat
        "guided_json": _parse_response_format(body),
        **_common_sampling(body),
    }


def models_response(models: List[str]) -> Dict[str, Any]:
    now = int(time.time())
    return {
        "object": "list",
        "data": [model_response(m, now) for m in models],
    }


def model_response(model: str, now: Optional[int] = None) -> Dict[str, Any]:
    """One model card (GET /v1/models/{id}, OpenAI retrieve-model)."""
    return {"id": model, "object": "model",
            "created": now or int(time.time()), "owned_by": "dynamo_tpu"}


def _token_bytes(token_text: str) -> List[int]:
    return list(token_text.encode("utf-8"))


def chat_logprob_entry(token_text: str, logprob: float,
                       top: List[tuple]) -> Dict[str, Any]:
    """One content entry of a chat choice's logprobs; `top` is
    [(token_text, logprob)] best-first."""
    return {
        "token": token_text,
        "logprob": logprob,
        "bytes": _token_bytes(token_text),
        "top_logprobs": [
            {"token": t, "logprob": lp, "bytes": _token_bytes(t)}
            for t, lp in top
        ],
    }


def chat_choice(index: int, text: str, finish_reason: str,
                logprob_entries: Optional[List[Dict]] = None,
                tool_call: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    out = {
        "index": index,
        "message": {"role": "assistant", "content": text},
        "finish_reason": finish_reason,
    }
    if tool_call is not None:
        out["message"] = {"role": "assistant", "content": None,
                          "tool_calls": [tool_call]}
        out["finish_reason"] = "tool_calls"
    if logprob_entries is not None:
        out["logprobs"] = {"content": logprob_entries}
    return out


def chat_completion_response(
    rid: str, model: str, choices: List[Dict[str, Any]],
    prompt_tokens: int, completion_tokens: int,
) -> Dict[str, Any]:
    return {
        "id": rid,
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": choices,
        "usage": _usage(prompt_tokens, completion_tokens),
    }


def chat_chunk(
    rid: str, model: str, delta: Dict[str, Any], finish_reason: Optional[str],
    with_usage_null: bool = False, index: int = 0,
    logprob_entries: Optional[List[Dict]] = None,
) -> Dict[str, Any]:
    choice: Dict[str, Any] = {
        "index": index, "delta": delta, "finish_reason": finish_reason,
    }
    if logprob_entries is not None:
        choice["logprobs"] = {"content": logprob_entries}
    out = {
        "id": rid,
        "object": "chat.completion.chunk",
        "created": int(time.time()),
        "model": model,
        "choices": [choice],
    }
    if with_usage_null:
        # with stream_options.include_usage, every non-final chunk carries
        # an explicit "usage": null per the OpenAI streaming contract
        out["usage"] = None
    return out


def completion_logprobs(tokens: List[str], token_logprobs: List[float],
                        top: List[List[tuple]]) -> Dict[str, Any]:
    """Legacy completions logprobs block; `top[i]` is [(text, lp)]."""
    offsets, pos = [], 0
    for t in tokens:
        offsets.append(pos)
        pos += len(t)
    return {
        "tokens": tokens,
        "token_logprobs": token_logprobs,
        "top_logprobs": [{t: lp for t, lp in alts} for alts in top],
        "text_offset": offsets,
    }


def completion_choice(index: int, text: str, finish_reason: str,
                      logprobs: Optional[Dict] = None) -> Dict[str, Any]:
    return {"index": index, "text": text, "finish_reason": finish_reason,
            "logprobs": logprobs}


def completion_response(
    rid: str, model: str, choices: List[Dict[str, Any]],
    prompt_tokens: int, completion_tokens: int,
) -> Dict[str, Any]:
    return {
        "id": rid,
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": choices,
        "usage": _usage(prompt_tokens, completion_tokens),
    }


def usage_chunk(
    rid: str, model: str, object_: str, prompt_tokens: int, completion_tokens: int
) -> Dict[str, Any]:
    """Final SSE chunk carrying usage, per stream_options.include_usage."""
    return {
        "id": rid,
        "object": object_,
        "created": int(time.time()),
        "model": model,
        "choices": [],
        "usage": _usage(prompt_tokens, completion_tokens),
    }


def map_finish_reason(reason: Optional[str]) -> str:
    # integrity_fault (watchdog sentinel tripped on this stream's device
    # output) surfaces as "error": the content is not trustworthy and
    # the client should retry — it must never look like a clean "stop"
    return {"stop": "stop", "length": "length", "abort": "stop",
            "kv_oom": "length", "integrity_fault": "error",
            }.get(reason or "stop", "stop")
