"""OpenAI-compatible request/response shaping (dict-level, stdlib-only).

API surface contract: /v1/models and /v1/chat/completions (+ /v1/completions)
exactly as the reference exposes them (/root/reference/README.md:277-292,
/root/reference/deploy-incluster.sh:497-501), including SSE streaming chunks.
"""

from __future__ import annotations

import time
import uuid
from typing import Any, Dict, List, Optional, Tuple


class BadRequest(Exception):
    pass


def new_id(prefix: str = "chatcmpl") -> str:
    return f"{prefix}-{uuid.uuid4().hex[:24]}"


def parse_chat_request(body: Dict[str, Any]) -> Dict[str, Any]:
    if not isinstance(body, dict):
        raise BadRequest("body must be a JSON object")
    messages = body.get("messages")
    if not isinstance(messages, list) or not messages:
        raise BadRequest("'messages' must be a non-empty array")
    for m in messages:
        if not isinstance(m, dict) or "role" not in m or "content" not in m:
            raise BadRequest("each message needs 'role' and 'content'")
    model = body.get("model")
    if not isinstance(model, str) or not model:
        raise BadRequest("'model' is required")
    mt = body.get("max_tokens", body.get("max_completion_tokens", 512))
    if not isinstance(mt, int) or mt < 1:
        raise BadRequest("'max_tokens' must be a positive integer")
    temperature = _num(body, "temperature", 1.0)
    if temperature < 0:
        raise BadRequest("'temperature' must be >= 0")
    return {
        "model": model,
        "messages": messages,
        "max_tokens": mt,
        "temperature": temperature,
        "top_p": _num(body, "top_p", 1.0),
        "top_k": int(_num(body, "top_k", 0)),
        "stream": bool(body.get("stream", False)),
        "include_usage": _include_usage(body),
        "ignore_eos": bool(body.get("ignore_eos", False)),
    }


def _include_usage(body: Dict[str, Any]) -> bool:
    so = body.get("stream_options") or {}
    if not isinstance(so, dict):
        raise BadRequest("'stream_options' must be an object")
    return bool(so.get("include_usage", False))


def _usage(prompt_tokens: int, completion_tokens: int) -> Dict[str, int]:
    return {
        "prompt_tokens": prompt_tokens,
        "completion_tokens": completion_tokens,
        "total_tokens": prompt_tokens + completion_tokens,
    }


def _num(body: Dict[str, Any], key: str, default: float) -> float:
    v = body.get(key, default)
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise BadRequest(f"'{key}' must be a number")
    return float(v)


def parse_completion_request(body: Dict[str, Any]) -> Dict[str, Any]:
    if not isinstance(body, dict):
        raise BadRequest("body must be a JSON object")
    prompt = body.get("prompt")
    if isinstance(prompt, list):
        if not prompt or not all(isinstance(p, str) for p in prompt):
            raise BadRequest("'prompt' array must contain strings")
        prompt = prompt[0]
    if not isinstance(prompt, str):
        raise BadRequest("'prompt' must be a string")
    model = body.get("model")
    if not isinstance(model, str) or not model:
        raise BadRequest("'model' is required")
    mt = body.get("max_tokens", 16)
    if not isinstance(mt, int) or mt < 1:
        raise BadRequest("'max_tokens' must be a positive integer")
    return {
        "model": model,
        "prompt": prompt,
        "max_tokens": mt,
        "temperature": _num(body, "temperature", 1.0),
        "top_p": _num(body, "top_p", 1.0),
        "top_k": int(_num(body, "top_k", 0)),
        "stream": bool(body.get("stream", False)),
        "include_usage": _include_usage(body),
        "ignore_eos": bool(body.get("ignore_eos", False)),
    }


def models_response(models: List[str]) -> Dict[str, Any]:
    now = int(time.time())
    return {
        "object": "list",
        "data": [
            {"id": m, "object": "model", "created": now, "owned_by": "dynamo_tpu"}
            for m in models
        ],
    }


def chat_completion_response(
    rid: str, model: str, text: str, finish_reason: str,
    prompt_tokens: int, completion_tokens: int,
) -> Dict[str, Any]:
    return {
        "id": rid,
        "object": "chat.completion",
        "created": int(time.time()),
        "model": model,
        "choices": [
            {
                "index": 0,
                "message": {"role": "assistant", "content": text},
                "finish_reason": finish_reason,
            }
        ],
        "usage": _usage(prompt_tokens, completion_tokens),
    }


def chat_chunk(
    rid: str, model: str, delta: Dict[str, Any], finish_reason: Optional[str],
    with_usage_null: bool = False,
) -> Dict[str, Any]:
    out = {
        "id": rid,
        "object": "chat.completion.chunk",
        "created": int(time.time()),
        "model": model,
        "choices": [{"index": 0, "delta": delta, "finish_reason": finish_reason}],
    }
    if with_usage_null:
        # with stream_options.include_usage, every non-final chunk carries
        # an explicit "usage": null per the OpenAI streaming contract
        out["usage"] = None
    return out


def completion_response(
    rid: str, model: str, text: str, finish_reason: str,
    prompt_tokens: int, completion_tokens: int,
) -> Dict[str, Any]:
    return {
        "id": rid,
        "object": "text_completion",
        "created": int(time.time()),
        "model": model,
        "choices": [{"index": 0, "text": text, "finish_reason": finish_reason,
                     "logprobs": None}],
        "usage": _usage(prompt_tokens, completion_tokens),
    }


def usage_chunk(
    rid: str, model: str, object_: str, prompt_tokens: int, completion_tokens: int
) -> Dict[str, Any]:
    """Final SSE chunk carrying usage, per stream_options.include_usage."""
    return {
        "id": rid,
        "object": object_,
        "created": int(time.time()),
        "model": model,
        "choices": [],
        "usage": _usage(prompt_tokens, completion_tokens),
    }


def map_finish_reason(reason: Optional[str]) -> str:
    return {"stop": "stop", "length": "length", "abort": "stop",
            "kv_oom": "length"}.get(reason or "stop", "stop")
