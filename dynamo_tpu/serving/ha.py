"""HA frontend plane: journal-on-NATS, resume claims, gossiped tenant
counters, worker-registration gossip (docs/robustness.md "HA frontend
plane").

One frontend process used to hold four pieces of state that made it the
last SPOF in a stack whose workers are already hitless: the recovery
journal (serving/recovery.py), the KV event index, per-tenant admission
counts, and worker membership. This module replicates all four across N
frontend replicas over the SAME core-NATS plane the request path already
rides — no JetStream, no new dependency:

- **JournalPlane** — every worker ``dynr`` journal record a frontend
  relays (start record, seam checkpoints ``{n, c, t}``) is re-published
  on ``dynamo.journal.rec.<response-id>``; every frontend subscribes the
  wildcard into a bounded-LRU store, so a stream whose frontend dies can
  be resumed **byte-identically through a different frontend**: the
  client re-POSTs the original body plus ``dynamo_resume`` (response id
  + its own delivered-chars cursor), the surviving frontend rebuilds the
  PR 4 ``dynamo_recovery`` continuation from the stored record, re-picks
  a worker with ``relaxed_overlap``, and relays exactly the chars past
  the seam. The store reuses the journal's n-consistency check: a
  replica that joined mid-stream (missed checkpoints) marks its record
  invalid and REFUSES the resume rather than risking duplicate tokens.
- **Resume claims** — two frontends racing to resume the same response
  id resolve to a single winner: each publishes a claim (nonce + its
  frontend id) on the journal subject and wins only if its claim is the
  minimum after a short deterministic window. Against a JetStream-
  enabled nats-server this maps onto a real KV compare-and-set; over
  core pub/sub (the mini broker) the claim window provides the same
  single-winner guarantee for in-process delivery.
- **TenantGossip** — bounded-staleness approximate tenant in-flight
  counters: each frontend periodically publishes its per-tenant counts
  on ``dynamo.frontend.gossip.<frontend-id>``; peers fold fresh
  snapshots into admission (qos/tenancy.TenantAdmission.peer_counts_fn)
  so the PR 7 weighted caps and over-share predicate hold FLEET-wide.
  Shed decisions stay local — gossip only widens the counters.
- **WorkerGossip** — a worker heartbeating to one frontend is
  re-published to the others (``source="peer"``), so a replica that
  never heard the heartbeat directly does not TTL-purge a live worker.

Kill switch: a frontend without a NATS url simply has no HA plane —
single-frontend behavior is byte-identical to the pre-HA stack.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from dynamo_tpu.serving.nats import subject_token

log = logging.getLogger("dynamo_tpu.ha")

# journal records / claims for one response id; the token is
# subject_token(response_id) so arbitrary ids stay one subject token
JOURNAL_SUBJECT_PREFIX = "dynamo.journal.rec"
JOURNAL_WILDCARD = JOURNAL_SUBJECT_PREFIX + ".>"
# per-frontend tenant in-flight snapshots
GOSSIP_SUBJECT_PREFIX = "dynamo.frontend.gossip"
GOSSIP_WILDCARD = GOSSIP_SUBJECT_PREFIX + ".>"
# worker membership relays (register/deregister heard directly)
WORKERS_SUBJECT_PREFIX = "dynamo.frontend.workers"
WORKERS_WILDCARD = WORKERS_SUBJECT_PREFIX + ".>"

# client -> frontend: body extension requesting a cross-frontend resume
RESUME_BODY_KEY = "dynamo_resume"

FRONTEND_ID_ENV = "DYNAMO_TPU_FRONTEND_ID"
# peers whose last gossip snapshot is older than this are ignored — the
# staleness bound on the approximate counters
GOSSIP_STALE_ENV = "DYNAMO_TPU_GOSSIP_STALE_S"
DEFAULT_GOSSIP_STALE_S = 5.0
# periodic snapshot cadence (0 disables the publisher thread; tests call
# publish_now() for deterministic propagation)
GOSSIP_INTERVAL_ENV = "DYNAMO_TPU_GOSSIP_INTERVAL_S"
DEFAULT_GOSSIP_INTERVAL_S = 1.0
# resume-claim settle window: how long a claimant waits for competing
# claims before declaring itself the winner
CLAIM_WINDOW_ENV = "DYNAMO_TPU_CLAIM_WINDOW_S"
DEFAULT_CLAIM_WINDOW_S = 0.05

# journal store LRU bound (records, i.e. concurrently-tracked streams)
JOURNAL_CAP = 4096


def _env_float(name: str, default: float) -> float:
    try:
        return max(0.0, float(os.environ.get(name, default)))
    except ValueError:
        return default


def frontend_id() -> str:
    """Stable-ish identity for this frontend replica: the operator
    materializes the pod name into DYNAMO_TPU_FRONTEND_ID; standalone
    processes mint a random one (identity only needs to be unique, not
    persistent — a restarted frontend rebuilds all HA state from NATS)."""
    fid = (os.environ.get(FRONTEND_ID_ENV) or "").strip()
    return subject_token(fid) if fid else f"fe-{uuid.uuid4().hex[:10]}"


def journal_subject(rid: str) -> str:
    return f"{JOURNAL_SUBJECT_PREFIX}.{subject_token(rid)}"


def normalize_resume(rec: Any) -> Dict[str, Any]:
    """Validate an inbound ``dynamo_resume`` body extension. Raises
    ValueError on garbage — mapped to HTTP 400 upstream."""
    if not isinstance(rec, dict):
        raise ValueError(f"'{RESUME_BODY_KEY}' must be an object")
    rid = rec.get("response_id")
    if not isinstance(rid, str) or not rid or len(rid) > 80 \
            or not rid.isprintable():
        raise ValueError("'response_id' must be a short printable string")
    delivered = rec.get("delivered_chars", 0)
    if isinstance(delivered, bool) or not isinstance(delivered, int) \
            or delivered < 0:
        raise ValueError("'delivered_chars' must be a non-negative integer")
    return {"response_id": rid, "delivered_chars": int(delivered)}


class JournalRecord:
    """One stream's replicated recovery journal, rebuilt from the worker's
    own ``dynr`` records as relayed by whichever frontend owns the stream."""

    __slots__ = ("rid", "tokens", "checkpoint_chars", "seed", "resume_key",
                 "origin", "valid", "started", "done", "updated", "claims")

    def __init__(self, rid: str):
        self.rid = rid
        self.tokens: List[int] = []
        self.checkpoint_chars = 0
        self.seed: Optional[int] = None
        self.resume_key: Optional[List[int]] = None
        self.origin: Optional[str] = None  # frontend id that relayed last
        # valid flips False on an n-gap (this replica missed checkpoints);
        # started requires the start record (carries the pinned seed) —
        # both must hold for a resume to be safe
        self.valid = True
        self.started = False
        self.done = False
        self.updated = time.monotonic()
        # claimant fid -> (nonce, received_monotonic); stale claims expire
        # so a claimant that crashed after winning cannot block resumes
        self.claims: Dict[str, tuple] = {}

    def apply(self, rec: Dict) -> None:
        """Apply one worker journal record (the exact objects
        recovery.RequestJournal.apply_comment consumes)."""
        self.updated = time.monotonic()
        start = rec.get("start")
        if isinstance(start, dict):
            self.started = True
            if start.get("seed") is not None:
                try:
                    self.seed = int(start["seed"])
                except (TypeError, ValueError):
                    pass
            return
        try:
            self.tokens.extend(int(t) for t in (rec.get("t") or []))
        except (TypeError, ValueError):
            self.valid = False
            return
        n = rec.get("n")
        if n is not None and int(n) != len(self.tokens):
            # same invariant as the live RequestJournal: a dropped or
            # reordered checkpoint corrupts the seam — refuse to resume
            # rather than risk duplicated tokens
            self.valid = False
        if rec.get("c") is not None:
            try:
                self.checkpoint_chars = int(rec["c"])
            except (TypeError, ValueError):
                self.valid = False
        if rec.get("key") is not None:
            try:
                self.resume_key = [int(k) for k in rec["key"]][:2]
            except (TypeError, ValueError):
                pass

    @property
    def resumable(self) -> bool:
        return self.valid and self.started and not self.done


class JournalPlane:
    """Replicated journal store + resume-claim protocol over one NATS
    subject family. Each frontend both publishes the records of streams
    it relays and subscribes the wildcard, so every replica converges on
    the same (bounded-LRU) view."""

    def __init__(self, nats, fid: str, cap: int = JOURNAL_CAP,
                 claim_window_s: Optional[float] = None):
        import collections

        self.nats = nats
        self.fid = fid
        self.cap = cap
        self.claim_window_s = (
            claim_window_s if claim_window_s is not None
            else _env_float(CLAIM_WINDOW_ENV, DEFAULT_CLAIM_WINDOW_S))
        self._records: "collections.OrderedDict[str, JournalRecord]" = (  # guarded_by: _lock
            collections.OrderedDict())
        self._lock = threading.Lock()
        # wired by the frontend to dynamo_frontend_ha_* counters
        self.published_counter = None
        self.applied_counter = None
        self.published_total = 0
        self.applied_total = 0
        if nats is not None:
            nats.subscribe(JOURNAL_WILDCARD, self._on_msg)

    # ------------------------------------------------------------ publish --
    def _publish(self, rid: str, envelope: Dict) -> None:
        if self.nats is None:
            return
        envelope["rid"] = rid
        envelope["origin"] = self.fid
        try:
            self.nats.publish(journal_subject(rid),
                              json.dumps(envelope,
                                         separators=(",", ":")).encode())
        except (OSError, ConnectionError) as e:
            # the plane is advisory for the OWNING stream (its live
            # RequestJournal still recovers locally); peers just see a
            # gap and mark the record non-resumable
            log.debug("journal publish failed for %s: %s", rid, e)
            return
        self.published_total += 1
        if self.published_counter is not None:
            self.published_counter.inc(direction="published")

    def publish_record(self, rid: str, raw: bytes) -> None:
        """Re-publish one worker ``dynr`` record (raw JSON bytes as parsed
        off the SSE comment) under the stream's response id."""
        try:
            rec = json.loads(raw)
        except (ValueError, UnicodeDecodeError):
            return
        if isinstance(rec, dict):
            self._publish(rid, {"rec": rec})

    def publish_done(self, rid: str) -> None:
        """Tombstone: the stream completed ([DONE] delivered) — peers must
        refuse resumes instead of re-running generation past EOS."""
        self._publish(rid, {"done": True})

    # ------------------------------------------------------------ receive --
    def _on_msg(self, msg) -> None:
        try:
            obj = json.loads(msg.data)
        except (ValueError, UnicodeDecodeError):
            return
        if not isinstance(obj, dict):
            return
        rid = obj.get("rid")
        if not isinstance(rid, str) or not rid:
            return
        with self._lock:
            rec = self._records.get(rid)
            if rec is None:
                rec = self._records[rid] = JournalRecord(rid)
            else:
                self._records.move_to_end(rid)
            origin = obj.get("origin")
            if isinstance(origin, str):
                rec.origin = origin
            claim = obj.get("claim")
            if isinstance(claim, dict):
                fid, nonce = claim.get("fid"), claim.get("nonce")
                if isinstance(fid, str) and isinstance(nonce, str):
                    rec.claims[fid] = (nonce, time.monotonic())
            elif obj.get("done"):
                rec.done = True
                rec.claims.clear()
            elif isinstance(obj.get("rec"), dict):
                rec.apply(obj["rec"])
            while len(self._records) > self.cap:
                self._records.popitem(last=False)
        self.applied_total += 1
        if self.applied_counter is not None:
            self.applied_counter.inc(direction="applied")

    # ------------------------------------------------------------- lookup --
    def lookup(self, rid: str) -> Optional[JournalRecord]:
        with self._lock:
            return self._records.get(rid)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    # -------------------------------------------------------------- claim --
    def claim(self, rid: str, nonce: Optional[str] = None,
              window_s: Optional[float] = None) -> bool:
        """Single-winner resume claim. Publish (nonce, fid) on the journal
        subject, wait the settle window for competing claims, and win only
        if ours orders first. Core-NATS emulation of a KV compare-and-set:
        with reliable in-process delivery exactly one claimant sees itself
        as the minimum; a JetStream deployment would CAS the claim key
        instead and skip the window."""
        nonce = nonce if nonce is not None else uuid.uuid4().hex
        window = (window_s if window_s is not None else self.claim_window_s)
        self._publish(rid, {"claim": {"fid": self.fid, "nonce": nonce}})
        if window > 0:
            time.sleep(window)
        # only claims fresher than the settle horizon compete: a claimant
        # that crashed after winning ages out instead of blocking forever
        horizon = time.monotonic() - max(2.0 * window, 1.0)
        with self._lock:
            rec = self._records.get(rid)
            claims = {fid: n for fid, (n, ts) in rec.claims.items()
                      if ts >= horizon} if rec is not None else {}
        # defensive: our own claim must count even if the broker did not
        # echo it back yet (publisher-side network hiccup)
        claims.setdefault(self.fid, nonce)
        winner = min(claims.items(), key=lambda kv: (kv[1], kv[0]))[0]
        return winner == self.fid

    def release_claim(self, rid: str) -> None:
        """Drop every claim on `rid` (the winner finished or gave up, so a
        later resume attempt must not lose to a ghost claim)."""
        with self._lock:
            rec = self._records.get(rid)
            if rec is not None:
                rec.claims.clear()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"streams": len(self._records),
                    "published": self.published_total,
                    "applied": self.applied_total}


class TenantGossip:
    """Bounded-staleness per-tenant in-flight counters across the frontend
    fleet. Each replica publishes its own TenantAdmission counts (snapshot
    + monotonic seq, so late/reordered core-NATS deliveries can't rewind a
    peer's view); receivers keep the freshest snapshot per peer and ignore
    anything older than the staleness bound. ``peer_counts()`` is the fold
    TenantAdmission consumes — admission DECISIONS stay local."""

    def __init__(self, nats, fid: str, admission,
                 interval_s: Optional[float] = None,
                 stale_s: Optional[float] = None):
        self.nats = nats
        self.fid = fid
        self.admission = admission
        self.interval_s = (
            interval_s if interval_s is not None
            else _env_float(GOSSIP_INTERVAL_ENV, DEFAULT_GOSSIP_INTERVAL_S))
        self.stale_s = (stale_s if stale_s is not None
                        else _env_float(GOSSIP_STALE_ENV,
                                        DEFAULT_GOSSIP_STALE_S))
        self._seq = 0
        # peer fid -> (received_monotonic, seq, {tenant: inflight})
        self._peers: Dict[str, tuple] = {}
        self._lock = threading.Lock()
        self.sent_total = 0
        self.received_total = 0
        self.gossip_counter = None  # wired to dynamo_frontend_ha_gossip_*
        self._stop = threading.Event()
        if nats is not None:
            nats.subscribe(GOSSIP_WILDCARD, self._on_msg)
            if self.interval_s > 0:
                threading.Thread(target=self._publish_loop, daemon=True,
                                 name="tenant-gossip").start()

    def _publish_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.publish_now()

    def stop(self) -> None:
        self._stop.set()

    def publish_now(self) -> None:
        if self.nats is None:
            return
        with self._lock:
            self._seq += 1
            seq = self._seq
        counts = self.admission.snapshot()["inflight"]
        payload = json.dumps(
            {"fid": self.fid, "seq": seq, "inflight": counts},
            separators=(",", ":")).encode()
        try:
            self.nats.publish(f"{GOSSIP_SUBJECT_PREFIX}.{self.fid}", payload)
        except (OSError, ConnectionError):
            return  # this round is lost; the next snapshot supersedes it
        self.sent_total += 1
        if self.gossip_counter is not None:
            self.gossip_counter.inc(direction="sent")

    def _on_msg(self, msg) -> None:
        try:
            obj = json.loads(msg.data)
        except (ValueError, UnicodeDecodeError):
            return
        if not isinstance(obj, dict):
            return
        fid = obj.get("fid")
        if not isinstance(fid, str) or fid == self.fid:
            return
        counts = obj.get("inflight")
        if not isinstance(counts, dict):
            return
        try:
            seq = int(obj.get("seq", 0))
        except (TypeError, ValueError):
            return
        clean = {str(t): int(n) for t, n in counts.items()
                 if isinstance(n, int) and not isinstance(n, bool) and n >= 0}
        with self._lock:
            prev = self._peers.get(fid)
            if prev is not None and prev[1] >= seq:
                return  # stale/reordered snapshot must not rewind the view
            self._peers[fid] = (time.monotonic(), seq, clean)
        self.received_total += 1
        if self.gossip_counter is not None:
            self.gossip_counter.inc(direction="received")

    def peer_counts(self) -> Dict[str, int]:
        """Per-tenant in-flight summed over peers with a FRESH snapshot
        (the staleness bound: a dead peer's load stops counting against
        tenant caps within stale_s)."""
        cutoff = time.monotonic() - self.stale_s
        out: Dict[str, int] = {}
        with self._lock:
            for ts, _seq, counts in self._peers.values():
                if ts < cutoff:
                    continue
                for t, n in counts.items():
                    out[t] = out.get(t, 0) + n
        return out

    def live_peers(self) -> int:
        cutoff = time.monotonic() - self.stale_s
        with self._lock:
            return sum(1 for ts, _s, _c in self._peers.values()
                       if ts >= cutoff)

    def stats(self) -> Dict[str, Any]:
        return {"fid": self.fid, "live_peers": self.live_peers(),
                "peer_inflight": self.peer_counts(),
                "sent": self.sent_total, "received": self.received_total}


class WorkerGossip:
    """Relay worker membership between frontend replicas: a register or
    deregister heard DIRECTLY (HTTP heartbeat) is re-published; peers
    apply it with ``source="peer"`` — which, like etcd merges, never
    clobbers a fresh direct heartbeat — so a worker heartbeating to one
    replica stays registered (and TTL-refreshed) on all of them."""

    def __init__(self, nats, fid: str, router):
        self.nats = nats
        self.fid = fid
        self.router = router
        self.relayed_total = 0
        self.applied_total = 0
        if nats is not None:
            nats.subscribe(WORKERS_WILDCARD, self._on_msg)

    def _publish(self, payload: Dict) -> None:
        if self.nats is None:
            return
        payload["fid"] = self.fid
        try:
            self.nats.publish(f"{WORKERS_SUBJECT_PREFIX}.{self.fid}",
                              json.dumps(payload,
                                         separators=(",", ":")).encode())
            self.relayed_total += 1
        except (OSError, ConnectionError):
            pass  # peers fall back to their own TTL view

    def publish_register(self, url: str, model: str, mode: str,
                         stats: Optional[Dict]) -> None:
        self._publish({"op": "register", "url": url, "model": model,
                       "mode": mode, "stats": stats})

    def publish_deregister(self, url: str) -> None:
        self._publish({"op": "deregister", "url": url})

    def _on_msg(self, msg) -> None:
        try:
            obj = json.loads(msg.data)
        except (ValueError, UnicodeDecodeError):
            return
        if not isinstance(obj, dict) or obj.get("fid") == self.fid:
            return
        url = obj.get("url")
        if not isinstance(url, str) or not url:
            return
        op = obj.get("op")
        if op == "register":
            self.router.register(url, str(obj.get("model", "?")),
                                 str(obj.get("mode", "agg")),
                                 obj.get("stats") if isinstance(
                                     obj.get("stats"), dict) else None,
                                 source="peer")
            self.applied_total += 1
        elif op == "deregister":
            # an explicit drain is authoritative everywhere: the worker
            # itself asked to stop receiving traffic
            self.router.deregister(url)
            self.applied_total += 1


def build_continuation(rec: JournalRecord,
                       delivered_chars: int) -> Dict[str, Any]:
    """The PR 4 ``dynamo_recovery`` body extension for a cross-frontend
    resume: the replicated journal supplies the seam (tokens, seed,
    sampler resume key); the CLIENT supplies its own delivered-chars
    cursor — the dying frontend's delivered count died with it, and the
    checkpoint-before-data invariant guarantees the journal covers
    everything any client actually saw."""
    return {
        "prior_tokens": list(rec.tokens),
        "delivered_chars": int(delivered_chars),
        "seed": rec.seed,
        "resume_key": (None if rec.resume_key is None
                       else list(rec.resume_key)),
        "response_id": rec.rid,
        "role_sent": True,
    }
