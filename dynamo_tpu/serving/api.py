"""OpenAI-compatible HTTP server serving a local Engine — the aggregated-worker
path, equivalent to the reference's engine worker + frontend collapsed into one
pod (/root/reference/examples/deploy/vllm/agg.yaml).

Endpoints: GET /v1/models, POST /v1/chat/completions, POST /v1/completions
(both with SSE streaming), GET /metrics (Prometheus), GET /health, /live,
/ready, GET /worker/stats (router introspection).
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time
import urllib.parse
from typing import Any, Dict, List, Optional

from dynamo_tpu.engine.engine import Engine
from dynamo_tpu.engine.kv_cache import OutOfPages
from dynamo_tpu.engine.request import GenRequest
from dynamo_tpu.engine.tokenizer import get_tokenizer
from dynamo_tpu.observability import context as obs_context
from dynamo_tpu.observability import slo as obs_slo
from dynamo_tpu.observability import tracing as obs_tracing
from dynamo_tpu.robustness import faults
from dynamo_tpu.robustness.deadline import Deadline
from dynamo_tpu.serving import protocol as proto
from dynamo_tpu.serving import recovery
from dynamo_tpu.serving.engine_service import EngineService
from dynamo_tpu.serving.http_base import (
    JsonHTTPHandler,
    make_http_server,
    serve_forever_in_thread,  # noqa: F401  (re-export for callers/tests)
)
from dynamo_tpu.serving.metrics import FrontendMetrics, Gauge

log = logging.getLogger("dynamo_tpu.api")


class TraceBusy(RuntimeError):
    """A profiler capture is already in progress on this worker."""


# one-line descriptions behind GET /debug/ — the operator's map of the
# worker-side debug surface (the frontend has its own index)
WORKER_DEBUG_INDEX = {
    "/debug/spans": "recent request/engine spans (?trace_id=&n=)",
    "/debug/slo": "SLO attainment windows and violation breakdown",
    "/debug/flight": "engine flight recorder: per-step records with "
                     "batch composition, decisions, phase timings "
                     "(?n=&rid=&tenant=&kind=)",
    "/debug/costs": "per-tenant chip-seconds and HBM byte-seconds "
                    "attributed by the engine cost ledger",
    "/debug/timeline": "engine step timeline: exact phase intervals, "
                       "host-gap/bubble attribution "
                       "(?steps=&format=perfetto|summary|json&trace_id=)",
    "/debug/trace": "capture a jax.profiler trace zip (?duration_s=; "
                    "409 while another capture runs)",
}


class IncrementalDetokenizer:
    """Streaming detokenization with bounded re-decode (vLLM-style windows):
    each push decodes only the tokens since the last emitted boundary, holding
    back trailing bytes that don't yet form complete UTF-8."""

    def __init__(self, tokenizer):
        self.tok = tokenizer
        self.ids: List[int] = []
        self.prefix_offset = 0
        self.read_offset = 0
        self.emitted = ""

    def push(self, token_id: int) -> str:
        self.ids.append(token_id)
        prefix_text = self.tok.decode(self.ids[self.prefix_offset:self.read_offset])
        new_text = self.tok.decode(self.ids[self.prefix_offset:])
        if new_text.endswith("�"):
            return ""
        delta = new_text[len(prefix_text):]
        self.prefix_offset = self.read_offset
        self.read_offset = len(self.ids)
        self.emitted += delta
        return delta


class StopStringMatcher:
    """Detokenizer-aware stop-string handling: holds back the longest
    possible partial match so a stop string arriving across token boundaries
    is never leaked to the client, and truncates the output at the match."""

    def __init__(self, stops: List[str]):
        self.stops = stops
        self.hold = max((len(s) for s in stops), default=1) - 1
        self.buf = ""
        self.stopped = False

    def push(self, delta: str) -> tuple:
        """Returns (text_to_emit, stopped)."""
        if self.stopped:
            return "", True
        self.buf += delta
        best = -1
        for s in self.stops:
            i = self.buf.find(s)
            if i >= 0 and (best < 0 or i < best):
                best = i
        if best >= 0:
            self.stopped = True
            emit, self.buf = self.buf[:best], ""
            return emit, True
        if self.hold <= 0 or len(self.buf) <= self.hold:
            if self.hold <= 0:
                emit, self.buf = self.buf, ""
                return emit, False
            return "", False
        cut = len(self.buf) - self.hold
        emit, self.buf = self.buf[:cut], self.buf[cut:]
        return emit, False

    def flush(self) -> str:
        emit, self.buf = self.buf, ""
        return emit


class GenerationHandle:
    """A submitted request plus its event stream — submission (and its
    validation errors) happens strictly before any response bytes."""

    def __init__(self, ctx: "ServingContext", rid: str, prompt_ids: List[int],
                 params: dict, index: int = 0, trace_span=None,
                 deadline: Optional[Deadline] = None):
        self.ctx = ctx
        self.rid = rid
        self.index = index
        self.span = trace_span if trace_span is not None \
            else obs_tracing.NOOP_SPAN
        self.deadline = deadline
        self.stops: List[str] = params.get("stop") or []
        self.want_logprobs = params.get("logprobs") is not None
        # --- mid-stream recovery continuation (serving/recovery.py) ---
        # the journaled tokens the original worker already emitted become
        # extra PREFILL (prompt ⊕ emitted tokens) with the remaining token
        # budget; prior_output_token_ids keeps penalties/guided replay
        # honest and resume_key restores the exact sampling chain — the
        # same correctness contract as preemption-by-recompute
        self.journal_sink = None  # set by the handler on journaled streams
        rec = params.get("_recovery") if index == 0 else None
        self.recovery = rec
        prior = list(rec["prior_tokens"]) if rec else []
        self.prior_count = len(prior)
        max_tokens = params["max_tokens"]
        if prior:
            prompt_ids = list(prompt_ids) + prior
            max_tokens = max(1, max_tokens - len(prior))
        self.prompt_ids = prompt_ids
        # each choice of an n>1 request gets its own deterministic chain
        seed = params.get("seed")
        # user stop_token_ids pass through UNMODIFIED: the model-EOS merge
        # lives in engine._stop_ids_for (which knows model_cfg and the
        # ignore_eos exemption), so ignore_eos=true + stop_token_ids no
        # longer stops on model EOS (vLLM semantics)
        stop_ids = list(params.get("stop_token_ids") or [])
        self.req = GenRequest(
            rid,
            list(prompt_ids),
            max_tokens=max_tokens,
            temperature=params["temperature"],
            top_p=params["top_p"],
            top_k=params["top_k"],
            presence_penalty=params.get("presence_penalty", 0.0),
            frequency_penalty=params.get("frequency_penalty", 0.0),
            min_p=params.get("min_p", 0.0),
            logit_bias=params.get("logit_bias"),
            seed=None if seed is None else seed + index,
            logprobs=params.get("logprobs"),
            ignore_eos=params.get("ignore_eos", False),
            priority=params.get("priority", 0),
            guided_json=params.get("guided_json", False),
            stop_token_ids=stop_ids,
            prior_output_token_ids=prior,
            resume_key=(rec or {}).get("resume_key"),
            adapter=params.get("adapter"),
            # per-tenant QoS: the identity the handler resolved from the
            # request headers rides into the engine's weighted-fair
            # scheduler (and across preemption/recovery continuations)
            tenant=params.get("tenant"),
        )
        self.tenant = self.req.tenant or "default"
        ctx.metrics.tenant_requests.inc(tenant=self.tenant)
        if self.req.adapter and ctx.lora_requests_total is not None:
            ctx.lora_requests_total.inc(adapter=self.req.adapter)
            if ctx.engine.lora is not None:
                ctx.engine.lora.note_request(self.req.adapter)
        if ctx.disagg_client is not None:
            # decode role: prefill remotely, pull KV, continue locally
            self.queue = ctx.disagg_client.start(self.req,
                                                 parent_span=self.span,
                                                 deadline=deadline)
        else:
            self.queue = ctx.service.submit(self.req)  # raises ValueError early
        ctx.metrics.requests_total.inc(model=ctx.served_model)
        ctx.metrics.isl.observe(len(prompt_ids), model=ctx.served_model)
        # collected when logprobs were requested: one protocol entry per token
        self.lp_entries: List[dict] = []

    def _lp_entry(self, ev) -> Optional[dict]:
        """Build (but don't commit) the protocol logprob entry for a token."""
        if not (self.want_logprobs and ev.logprob is not None):
            return None
        tok = self.ctx.tokenizer
        return proto.chat_logprob_entry(
            tok.decode([ev.token_id]), ev.logprob,
            [(tok.decode([tid]), lp) for tid, lp in (ev.top_logprobs or [])],
        )

    def _first_token_spans(self, ev, ttft_s: float):
        """Bridge the engine's per-request phase timings (TokenEvent.phase,
        recorded by the same prefill paths that feed the PhaseTimer
        histograms) into back-dated worker.queue / worker.prefill child
        spans, then open the worker.decode span. Engine-wide PhaseTimer
        quantiles ride as attributes so a single slow trace carries the
        fleet context it should be judged against."""
        if not self.span.recording:
            return None
        tracer = self.ctx.tracer
        eng = self.ctx.engine
        if self.req.adapter and eng.lora is not None:
            # the device slot is known once admission resolved it
            self.span.set_attributes({
                "lora.adapter": self.req.adapter,
                "lora.slot": eng.lora.slot_of(self.req.adapter) or 0,
            })
        eng_ph = eng.metrics.phases
        t_first_ns = time.time_ns()
        phase = ev.phase or {}
        queue_ns = int(phase.get("queue_s", 0.0) * 1e9)
        prefill_ns = int(phase.get("prefill_s", 0.0) * 1e9)
        pf_start_ns = t_first_ns - prefill_ns
        if queue_ns or prefill_ns:
            tracer.start_span(
                "worker.queue", parent=self.span,
                start_ns=pf_start_ns - queue_ns).end(end_ns=pf_start_ns)
            tracer.start_span(
                "worker.prefill", parent=self.span, start_ns=pf_start_ns,
                attributes={
                    "prompt_tokens": len(self.prompt_ids),
                    "engine.prefill.p50_ms":
                        round(eng_ph["prefill"].quantile_ms(0.5), 3),
                    "engine.prefill.p95_ms":
                        round(eng_ph["prefill"].quantile_ms(0.95), 3),
                }).end(end_ns=t_first_ns)
        return tracer.start_span(
            "worker.decode", parent=self.span, start_ns=t_first_ns,
            attributes={"ttft_s": round(ttft_s, 6)})

    def run(self, emit) -> tuple:
        """Drive the stream; emit(delta, finish|None, lp_entry|None) -> bool
        keeps going while True. A False return (client gone) aborts the
        engine request.

        Returns (text, finish_reason, completion_tokens)."""
        ctx, m = self.ctx, self.ctx.metrics
        model = ctx.served_model
        t0 = time.monotonic()
        t_prev: Optional[float] = None
        decode_span = None
        detok = IncrementalDetokenizer(ctx.tokenizer)
        matcher = StopStringMatcher(self.stops) if self.stops else None
        text_parts: List[str] = []
        n_out = 0
        finish = "stop"
        # --- recovery journal bookkeeping (serving/recovery.py) ---
        consumed = self.prior_count  # tokens covered by the journal
        content_total = 0  # cumulative content chars (incl. primed text)
        pending_journal: List[int] = []  # tokens since the last checkpoint

        def checkpoint(extra: Optional[dict] = None) -> None:
            """Flush a journal checkpoint BEFORE the delta it covers goes
            on the wire — the journal may run ahead of delivery, never
            behind, which is the exactly-once seam invariant."""
            nonlocal pending_journal
            entry = {"n": consumed, "c": content_total, "t": pending_journal}
            if extra:
                entry.update(extra)
            pending_journal = []
            self.journal_sink(entry)

        if self.recovery is not None:
            # continuation: replay the journaled tokens through a fresh
            # detok/matcher pipeline (deterministic, so its output is
            # byte-identical to what the original worker delivered) and
            # re-emit exactly the chars past delivered_chars — the seam
            primed_parts: List[str] = []
            stopped_in_prior = False
            for t in self.recovery["prior_tokens"]:
                d = detok.push(t)
                if matcher is not None and not stopped_in_prior:
                    d, stopped_in_prior = matcher.push(d)
                primed_parts.append(d)
            primed = "".join(primed_parts)
            content_total = len(primed)
            catch_up = primed[self.recovery["delivered_chars"]:]
            if self.journal_sink is not None:
                checkpoint()
            if stopped_in_prior:
                # the stop string had fully arrived before the original
                # stream died: nothing left to generate
                text_parts.append(catch_up)
                emit(catch_up, "stop", None)
                ctx.service.abort(self.rid)
                m.duration.observe(time.monotonic() - t0, model=model)
                m.osl.observe(0, model=model)
                return catch_up, "stop", 0
            if catch_up:
                text_parts.append(catch_up)
                emit(catch_up, None, None)
        # the drain timeout is the request's REMAINING deadline budget
        # (frontend hop time already subtracted), not a fixed 600 s
        drain_timeout = (self.deadline.remaining()
                         if self.deadline is not None else None)
        for ev in ctx.service.drain(self.req, self.queue,
                                    timeout=drain_timeout):
            if (self.journal_sink is not None and not ev.finished
                    and ctx.drain_handoff.is_set()):
                # graceful drain, ACTIVE handoff: snapshot the sampling
                # chain, push the journal tail back to the frontend as
                # the final comment, and abort — the frontend splices a
                # continuation onto the same client stream elsewhere
                st = ctx.service.sampling_state(self.rid)
                checkpoint({"handoff": 1,
                            **({"key": st["key"]} if st else {})})
                ctx.service.abort(self.rid)
                finish = "handoff"
                break
            now = time.monotonic()
            # exemplar: the request's trace id rides the latency buckets,
            # so a p99 bucket resolves at /debug/spans?trace_id=...
            ex = self.span.trace_id if self.span.recording else None
            if t_prev is None:
                m.ttft.observe(now - t0, exemplar=ex, model=model)
                m.tenant_ttft.observe(now - t0, tenant=self.tenant)
                decode_span = self._first_token_spans(ev, now - t0)
            else:
                m.itl.observe(now - t_prev, exemplar=ex, model=model)
                m.tenant_itl.observe(now - t_prev, tenant=self.tenant)
            t_prev = now
            delta = ""
            lp_entry = None
            if ev.token_id >= 0:
                n_out += 1
                consumed += 1
                pending_journal.append(ev.token_id)
                if ev.finished and ev.finish_reason == "stop":
                    # the finishing stop TOKEN is not content: HF decode
                    # skips specials, but the byte tokenizer cannot (a
                    # stop id < 256 would leak as a control byte), and
                    # logprobs must describe the returned text
                    pass
                else:
                    delta = detok.push(ev.token_id)
                    lp_entry = self._lp_entry(ev)
            stopped = False
            if matcher is not None and (delta or ev.finished):
                delta, stopped = matcher.push(delta)
                if not stopped and ev.finished:
                    delta += matcher.flush()
            if stopped:
                # stop string seen: truncate the text, DISCARD the stop
                # token's logprob entry (logprobs must match the returned
                # content), abort the engine side, report finish "stop"
                text_parts.append(delta)
                if self.journal_sink is not None and pending_journal:
                    if delta:
                        content_total += len(delta)
                    checkpoint()
                emit(delta, "stop", None)
                if not ev.finished:
                    ctx.service.abort(self.rid)
                finish = "stop"
                break
            if lp_entry is not None:
                self.lp_entries.append(lp_entry)
            fr = proto.map_finish_reason(ev.finish_reason) if ev.finished else None
            if ev.finished:
                finish = fr or "stop"
            text_parts.append(delta)
            if self.journal_sink is not None and pending_journal:
                # checkpoint EVERY consumed token, not just content-
                # bearing ones: a held-back token (UTF-8 / stop-string
                # holdback) is still committed state a continuation must
                # not re-sample differently — and the comment still lands
                # before the delta it may cover
                if delta:
                    content_total += len(delta)
                checkpoint()
            # emit on no-delta events too when they carry a logprob entry
            # (UTF-8 holdback): streaming logprobs are one entry per token
            if delta or ev.finished or lp_entry is not None:
                if not emit(delta, fr, lp_entry) and not ev.finished:
                    log.info("client disconnected; aborting %s", self.rid)
                    ctx.service.abort(self.rid)
                    finish = "abort"
                    break
        dur = time.monotonic() - t0
        m.duration.observe(
            dur, exemplar=(self.span.trace_id if self.span.recording
                           else None), model=model)
        m.osl.observe(n_out, model=model)
        ctx.kv_gauge.set(ctx.engine.allocator.free_pages)
        if decode_span is not None:
            eng_ph = ctx.engine.metrics.phases
            decode_span.set_attributes({
                "completion_tokens": n_out,
                "finish_reason": finish,
                "engine.decode_step.p50_ms":
                    round(eng_ph["decode_step"].quantile_ms(0.5), 3),
                "engine.decode_step.p95_ms":
                    round(eng_ph["decode_step"].quantile_ms(0.95), 3),
            })
            decode_span.end()
        if (self.span.recording
                and dur >= obs_tracing.slow_request_threshold_s()):
            log.warning(
                "slow request %s: %.2fs model=%s trace_id=%s — "
                "GET /debug/spans?trace_id=%s", self.rid, dur, model,
                self.span.trace_id, self.span.trace_id)
        return "".join(text_parts), finish, n_out


# spot reclamation: default drain deadline when a /internal/reclaim
# notice arrives without one (cloud maintenance notices are typically
# 30-120s; align with the preemptible node pool's advertised grace)
RECLAIM_DEADLINE_ENV = "DYNAMO_TPU_RECLAIM_DEADLINE_S"
DEFAULT_RECLAIM_DEADLINE_S = 60.0

# hitless weight rollout (docs/robustness.md "Hitless weight rollout"):
# how /internal/rollout flips a busy engine when the request doesn't name
# a mode — `finish` arms the flip (in-flight streams complete on the old
# version, admissions hold), `handoff` pushes journaled streams' seams to
# the frontend for resume on a still-old-version peer and flips as soon
# as the engine empties (bounded by the grace below, then falls back to
# an armed finish flip for any non-journaled stragglers)
ROLLOUT_DRAIN_MODE_ENV = "DYNAMO_TPU_ROLLOUT_DRAIN_MODE"
ROLLOUT_HANDOFF_GRACE_S = 5.0


def _env_reclaim_deadline_s() -> float:
    try:
        return max(1.0, float(os.environ.get(RECLAIM_DEADLINE_ENV,
                                             DEFAULT_RECLAIM_DEADLINE_S)))
    except ValueError:
        return DEFAULT_RECLAIM_DEADLINE_S


class ServingContext:
    """Everything the request handlers need, bundled for the handler class."""

    def __init__(self, engine: Engine, served_model: str,
                 prefill_urls=None, frontend_url=None, kvbm_peers=None):
        self.engine = engine
        self.service = EngineService(engine)
        self.served_model = served_model
        self.tokenizer = get_tokenizer(engine.cfg.model, engine.cfg.model_path)
        self.metrics = FrontendMetrics()
        # per-tenant QoS identity (dynamo_tpu.qos): the engine built the
        # registry from cfg.tenants / DYNAMO_TPU_TENANTS — handlers resolve
        # every inference request's tenant against the same classes the
        # weighted-fair scheduler budgets with
        self.tenants = engine.tenant_registry
        self.kv_gauge = Gauge(
            "dynamo_worker_kv_free_pages", "Free KV pages", self.metrics.registry
        )
        # --- multi-LoRA adapter serving (dynamo_tpu.lora) ---
        self.lora_requests_total = None
        self.lora_loaded_gauge = None
        if engine.lora is not None:
            from dynamo_tpu.serving.metrics import CallbackCounter, Counter

            self.lora_requests_total = Counter(
                "dynamo_lora_requests_total",
                "Requests served under a LoRA adapter, by adapter",
                self.metrics.registry, labelnames=("adapter",),
            )
            CallbackCounter(
                "dynamo_lora_swaps_total",
                "Adapter loads into a device slot (incl. LRU swap reloads)",
                self.metrics.registry,
                lambda: engine.lora.swaps_total,
            )
            self.lora_loaded_gauge = Gauge(
                "dynamo_lora_loaded",
                "Adapters resident in device slots right now",
                self.metrics.registry,
            )
        # --- KVBM tiered block manager (dynamo_tpu.kvbm) ---
        self.kv_event_publisher = None  # attached by the worker entrypoint
        self.kvbm_source = None  # peer-pull server over the transfer plane
        if engine.kvbm is not None:
            self.engine.kvbm.tracer = None  # set below with the tracer
            from dynamo_tpu.serving.metrics import CallbackCounter

            kvbm = engine.kvbm
            for name, help_, attr in (
                ("dynamo_kvbm_host_hits_total",
                 "Prefix lookups served from the KVBM host/disk tier",
                 "host_hits_total"),
                ("dynamo_kvbm_host_misses_total",
                 "Prefix lookup tails the KVBM tiers could not serve",
                 "host_misses_total"),
                ("dynamo_kvbm_demoted_blocks_total",
                 "KV blocks demoted from device to the host tier",
                 "demoted_blocks_total"),
                ("dynamo_kvbm_onboarded_blocks_total",
                 "KV blocks onboarded back onto the device",
                 "onboarded_blocks_total"),
                ("dynamo_kvbm_peer_onboarded_blocks_total",
                 "KV blocks onboarded from a peer worker's host tier",
                 "peer_onboarded_blocks_total"),
                ("dynamo_kvbm_removed_blocks_total",
                 "KV blocks dropped from every tier",
                 "removed_blocks_total"),
                ("dynamo_kvbm_gate_recompute_total",
                 "Onboards skipped because recompute beat restore",
                 "gate_recompute_total"),
            ):
                CallbackCounter(name, help_, self.metrics.registry,
                                (lambda k=kvbm, a=attr: getattr(k, a)))
            self.kvbm_blocks_gauge = Gauge(
                "dynamo_kvbm_host_blocks",
                "KVBM host-pool occupancy by state", self.metrics.registry,
                labelnames=("state",))
            from dynamo_tpu.transfer.kv_transfer import HostTierSource

            self.kvbm_source = HostTierSource(kvbm)
            log.info("kvbm host tier serving peers on port %d",
                     self.kvbm_source.port)
            if kvbm_peers:
                self._wire_kvbm_peers(kvbm, kvbm_peers)
        self.staged_kv_gauge = None  # registered with DeviceKVSource below
        self.preempt_gauge = Gauge(
            "dynamo_worker_preempted_sequences",
            "Sequences preempted (recompute) under KV page pressure",
            self.metrics.registry,
        )
        # --- engine watchdog (dynamo_tpu/robustness/watchdog.py): the
        # health state machine drives readiness, the /v1 shed gate, and
        # the planner's capacity view; trips hand journaled streams off
        # to a peer exactly like a pre-drain
        from dynamo_tpu.serving.metrics import CallbackCounterVec

        wd = engine.watchdog
        self.health_gauge = Gauge(
            "dynamo_engine_health",
            "Engine health state machine: 0=healthy 1=suspect "
            "2=resurrecting 3=quarantined",
            self.metrics.registry)
        self.health_gauge.set(wd.health_code)
        CallbackCounterVec("dynamo_engine_watchdog_trips_total",
             "Watchdog trips by kind (hung_dispatch, fatal_step)",
             self.metrics.registry,
             lambda: {(("kind", k),): v
                      for k, v in wd.summary()["trips_total"].items()},
             labelnames=("kind",))
        CallbackCounterVec("dynamo_engine_integrity_faults_total",
             "Integrity sentinel trips by sentinel "
             "(logits, decode_tokens, kv_checksum)",
             self.metrics.registry,
             lambda: {(("sentinel", s),): v
                      for s, v in
                      wd.summary()["integrity_faults_total"].items()},
             labelnames=("sentinel",))
        wd.on_trip = self._on_watchdog_trip
        wd.on_health = self._on_engine_health
        # --- live elasticity (dynamo_tpu/elasticity): the active weight
        # version as a labelled gauge (1 on the live label), refreshed at
        # scrape with label death so a flip/rollback never leaves a stale
        # version row next to the live one
        self.weight_version_gauge = Gauge(
            "dynamo_engine_weight_version",
            "Active weight version (1 on the live `version` label; the "
            "staged/rollback buffers show in "
            "dynamo_memory_staged_weights_bytes)",
            self.metrics.registry, labelnames=("version",),
        )
        self._exported_weight_version: Optional[str] = None
        self.start_time = time.time()
        # --- graceful drain (SIGTERM; docs/robustness.md "Recovery
        # semantics") --- draining sheds NEW inference requests with 503;
        # drain_handoff makes journaled in-flight streams push their
        # journal back to the frontend and abort, so the frontend can
        # splice a continuation on another worker
        self.draining = threading.Event()
        self.drain_handoff = threading.Event()
        # --- spot reclamation (docs/robustness.md "Preemptible batch
        # tier") --- a POST /internal/reclaim notice (or the node's
        # maintenance signal, wired by the worker entrypoint) runs the
        # same drain state machine under a HARD deadline; reclaim_cb is
        # the entrypoint's hook that also deregisters and stops serving
        self.reclaiming = threading.Event()
        self.reclaim_done = threading.Event()
        self.reclaim_deadline_s: Optional[float] = None
        self.reclaim_cb = None  # (deadline_s) -> None, set by the worker
        # operator manifest `preemptible: true` (spot/reclaimable pool):
        # advertised in the worker heartbeat so frontends and the planner
        # know which capacity can vanish on a reclamation notice
        self.preemptible = os.environ.get(
            "DYNAMO_TPU_PREEMPTIBLE", "0").lower() not in ("", "0", "false")
        self._trace_lock = threading.Lock()  # one profiler capture at a time
        # distributed request tracing: one tracer per serving role; spans
        # land in the process-global ring buffer behind GET /debug/spans
        self.tracer = obs_tracing.Tracer(
            f"worker-{engine.cfg.disaggregation_mode or 'agg'}")
        # --- SLO plane (observability/slo.py): per-role burn rate from
        # this worker's own latency histograms; the role selector lets one
        # manifest give prefill pools a TTFT SLO and decode pools an ITL
        # SLO (the per-pool signals planner v2 scales on)
        self.slo = obs_slo.SLOEngine(
            self.metrics, role=engine.cfg.disaggregation_mode or "agg")
        # --- engine phase/utilization exposition (observability/
        # engine_metrics.py): PhaseTimer histograms, batch occupancy,
        # jit-compile counters, live roofline MFU/MBU on /metrics
        from dynamo_tpu.observability.engine_metrics import (
            attach_engine_metrics,
        )

        self.engine_bridge = attach_engine_metrics(
            self.metrics.registry, engine)
        # --- memory/cost exposition (observability/memory.py): exact KV
        # pool accounting by tier/tenant, device memory_stats gauges, and
        # the per-tenant cost counters off the engine's CostLedger
        from dynamo_tpu.observability.memory import attach_memory_metrics

        self.memory_bridge = attach_memory_metrics(
            self.metrics.registry, engine)
        from dynamo_tpu.serving.metrics import CallbackCounter as _CC

        _CC("dynamo_spans_dropped_total",
            "Finished spans evicted from the ring buffer before any "
            "scrape could lift them (size: DYNAMO_TPU_TRACE_BUFFER)",
            self.metrics.registry,
            lambda: self.tracer.collector.dropped_total)
        if engine.kvbm is not None:
            # kvbm.offload / kvbm.onboard spans land in this worker's ring
            # buffer (GET /debug/spans) like every other worker span
            engine.kvbm.tracer = self.tracer

        # --- disaggregation wiring (mirrors the reference's role flags,
        # /root/reference/examples/deploy/sglang/disagg.yaml:45-52) ---
        self.kv_source = None
        self.kv_device_source = None
        self.disagg_client = None
        mode = engine.cfg.disaggregation_mode
        if mode == "prefill":
            from dynamo_tpu.transfer.kv_transfer import DeviceKVSource, KVSource

            self.kv_source = KVSource(
                engine, port=engine.cfg.disaggregation_bootstrap_port
            )
            log.info("prefill role: KV bootstrap on port %d", self.kv_source.port)
            if engine.cfg.disaggregation_transfer_backend == "ici":
                # cross-process leg of the ici plane: stage parked KV for
                # device-buffer pulls (TCP KVSource stays as the fallback)
                self.kv_device_source = DeviceKVSource(engine)
                # registered only alongside the source: workers without the
                # device plane must not expose a label-less zero series
                self.staged_kv_gauge = Gauge(
                    "dynamo_worker_staged_kv_gathers",
                    "Device-plane staged KV gathers by state (leaked = "
                    "expired un-released, still pinning HBM)",
                    self.metrics.registry, labelnames=("state",),
                )
        elif mode == "decode":
            from dynamo_tpu.serving.disagg import DisaggDecodeClient, PrefillPool

            self.disagg_client = DisaggDecodeClient(
                self, PrefillPool(prefill_urls, frontend_url)
            )

    def _wire_kvbm_peers(self, kvbm, peers) -> None:
        """Cross-worker onboard: on a host-tier miss, try each configured
        peer's host tier over the transfer plane (kv_transfer.fetch_host_
        blocks) before falling back to recompute."""
        from dynamo_tpu.transfer.kv_transfer import fetch_host_blocks

        parsed = []
        for p in peers:
            host, _, port = p.strip().rpartition(":")
            if host and port.isdigit():
                parsed.append((host, int(port)))
        if not parsed:
            return

        def peer_fetch(hashes):
            hexes = [h.hex() for h in hashes]
            for host, port in parsed:
                try:
                    got = fetch_host_blocks(host, port, hexes)
                except (ConnectionError, OSError, TimeoutError) as e:
                    log.debug("kvbm peer %s:%d unreachable: %s",
                              host, port, e)
                    continue
                if got:
                    return got
            return []

        kvbm.peer_fetch = peer_fetch
        log.info("kvbm cross-worker onboard enabled: %d peer(s)",
                 len(parsed))

    def register_kv_route(self, prompt_token_ids, routing_text: str) -> None:
        """Feed the KV event publisher one request's (token-chain,
        text-chain) association — `routing_text` must be the canonical
        text the FRONTEND hashes for routing (completions: the prompt
        string; chat: json.dumps(messages)). No-op without a publisher.
        The chain is seeded with the engine's ACTIVE weight-version
        namespace so the keys match what the engine publishes; a request
        that registers just before a flip and admits just after simply
        loses its routing events (the plane is advisory)."""
        if self.kv_event_publisher is None:
            return
        try:
            self.kv_event_publisher.register(
                prompt_token_ids, routing_text, self.engine.cfg.page_size,
                namespace=self.engine._kv_namespace(None))
        except Exception:
            log.exception("kv route registration failed")

    def refresh_weight_gauge(self) -> None:
        v = self.engine.weights.version
        prev = self._exported_weight_version
        if prev is not None and prev != v:
            self.weight_version_gauge.remove(version=prev)
        self.weight_version_gauge.set(1, version=v)
        self._exported_weight_version = v

    def attach_kv_event_publisher(self, publisher) -> None:
        self.kv_event_publisher = publisher
        self.engine.set_kv_event_sink(publisher.on_engine_event)

    def capture_trace(self, duration_s: float) -> bytes:
        """Capture a jax.profiler trace for `duration_s` and return it as a
        zip of the trace directory (XProf/TensorBoard-loadable). The
        in-engine tracing story from SURVEY §5 — the deployment-level SLA
        profiler (dynamo_tpu.profiler) covers pre-deploy planning; this
        covers live per-step behavior."""
        import io
        import shutil
        import tempfile
        import zipfile

        import jax

        # non-blocking: a capture sleeps up to 30s, and the old blocking
        # acquire parked a second HTTP thread for that whole window —
        # concurrent captures now fail fast (the route answers 409)
        if not self._trace_lock.acquire(blocking=False):
            raise TraceBusy("a profiler capture is already running")
        try:
            d = tempfile.mkdtemp(prefix="dynamo-trace-")
            try:
                jax.profiler.start_trace(d)
                # the capture window IS the critical section: _trace_lock
                # serializes profiler runs and the acquire above is
                # non-blocking (concurrent callers 409 instead of parking)
                time.sleep(min(max(duration_s, 0.05), 30.0))  # dynalint: off blocking-under-lock
                jax.profiler.stop_trace()
                buf = io.BytesIO()
                with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
                    for root, _, files in os.walk(d):
                        for f in files:
                            full = os.path.join(root, f)
                            z.write(full, os.path.relpath(full, d))
                return buf.getvalue()
            finally:
                # temp-dir cleanup before releasing the (non-blocking-
                # acquire) capture lock: a new capture must never race an
                # old capture's teardown for the profiler singleton
                shutil.rmtree(d, ignore_errors=True)  # dynalint: off blocking-under-lock
        finally:
            self._trace_lock.release()

    def begin_drain(self) -> None:
        """Stop admission NOW: new /v1 + /disagg/prefill requests shed 503
        (+ Retry-After) so a retrying client or the frontend's 503
        failover lands them on another replica. In-flight requests keep
        running until they finish or hand off."""
        self.draining.set()

    def _on_watchdog_trip(self, kind: str, seam: str) -> None:
        """Watchdog trip (monitor or scheduler thread): hand journaled
        in-flight streams off to a peer exactly like a pre-drain. The
        nudge is load-bearing — a wedged engine emits no TokenEvents, so
        blocked handlers would never observe drain_handoff without it."""
        self.request_handoff()
        self.service.nudge_all()

    def _on_engine_health(self, state: str) -> None:
        from dynamo_tpu.robustness.watchdog import HEALTH_CODES

        self.health_gauge.set(HEALTH_CODES.get(state, 0))
        if state == "healthy" and not self.draining.is_set():
            # resurrection done: stop asking streams to hand off — but
            # never un-drain a worker that is draining for its own
            # reasons (SIGTERM, reclaim, pre-drain)
            self.drain_handoff.clear()

    def request_handoff(self) -> None:
        """Ask journaled in-flight streams to hand off: each pushes its
        journal tail (token seam + sampling-key snapshot) back to the
        frontend as the final stream comment and aborts; the frontend
        splices a continuation on another worker. Non-journaled requests
        are unaffected (they finish or time out under the drain bound)."""
        self.drain_handoff.set()

    def drain_demote(self) -> int:
        """Demote every sole-owned prefix-cache page to the KVBM host
        tier (one batched device gather) so surviving peers can serve the
        departing worker's prefixes via the cross-worker host-tier fetch.
        No-op without a KVBM tier. Returns pages demoted."""
        eng = self.engine
        if eng.prefix_cache is None or eng.kvbm is None:
            return 0
        with eng._exec_lock:
            return eng.kvbm.demote_all(eng.prefix_cache)

    def drain(self, drain_s: float = 30.0,
              handoff_grace_s: float = 5.0) -> bool:
        """The drain state machine (worker SIGTERM / chaos tests):
        draining -> (grace: finish naturally) -> handoff -> quiesce ->
        demote KV to the host tier. Returns True when the engine emptied
        within the budget."""
        eng = self.engine
        self.begin_drain()
        t0 = time.monotonic()
        deadline = t0 + max(0.0, drain_s)
        grace_end = min(deadline, t0 + max(0.0, handoff_grace_s))
        while time.monotonic() < grace_end and (eng.num_active
                                                or eng.pending):
            time.sleep(0.05)
        if eng.num_active or eng.pending:
            self.request_handoff()
        while time.monotonic() < deadline and (eng.num_active
                                               or eng.pending):
            time.sleep(0.1)
        demoted = self.drain_demote()
        if demoted:
            log.info("drain: demoted %d prefix pages to the host tier",
                     demoted)
        return not (eng.num_active or eng.pending)

    def reclaim(self, deadline_s: float) -> Dict[str, Any]:
        """Spot/maintenance reclamation notice: this worker's capacity
        disappears in `deadline_s` seconds, hard. Runs the drain state
        machine with the deadline as its bound — handoff is requested
        almost immediately (natural-finish grace is at most a quarter of
        the notice, never the luxury 5s default), journaled streams push
        their seams to the frontend, prefix KV demotes to the host tier
        for peer fetch, and the entrypoint's reclaim_cb (when wired)
        deregisters and stops the server. Idempotent: a second notice
        reports the in-progress drain. Returns the ack payload."""
        eng = self.engine
        first = not self.reclaiming.is_set()
        if first:
            self.reclaiming.set()
            self.reclaim_deadline_s = deadline_s
            eng.flight.note(
                "reclaim", deadline_s=round(deadline_s, 3),
                active=eng.num_active, pending=len(eng.pending))
            log.warning("reclamation notice: %.1fs to drain %d active / "
                        "%d pending", deadline_s, eng.num_active,
                        len(eng.pending))
            self.begin_drain()
            cb = self.reclaim_cb

            def _run():
                try:
                    if cb is not None:
                        cb(deadline_s)
                    else:
                        self.drain(drain_s=deadline_s,
                                   handoff_grace_s=min(5.0,
                                                       deadline_s / 4.0))
                finally:
                    self.reclaim_done.set()

            threading.Thread(target=_run, daemon=True,
                             name="reclaim").start()
        return {"reclaiming": True, "first_notice": first,
                "deadline_s": self.reclaim_deadline_s,
                "active_seqs": eng.num_active,
                "pending": len(eng.pending)}

    def rollout(self, body: Dict[str, Any]) -> Dict[str, Any]:
        """POST /internal/rollout: the per-pod hot-swap control surface
        the operator's progressive fleet rollout drives (one action per
        request; `stage_flip` is the controller's single round trip).
        StageError maps to the handler's RuntimeError->503 path, so a
        refused stage (headroom, tree mismatch, version conflict) is
        retry-later to the controller and never touches the live tree."""
        from dynamo_tpu.elasticity.weights import StageError  # noqa: F401

        eng = self.engine
        wm = eng.weights
        action = (body.get("action") or "status").lower()
        if action == "status":
            out = wm.stats()
            out.update(active_seqs=eng.num_active,
                       pending=len(eng.pending))
            return out
        if action == "stage":
            return wm.stage(
                body.get("version") or "",
                model_path=body.get("model_path"),
                seed=body.get("seed"),
                quantization=body.get("quantization"))
        if action in ("flip", "stage_flip"):
            if action == "stage_flip":
                want = body.get("version") or ""
                if want and want == wm.version:
                    # idempotent: a controller retry after a timed-out
                    # round trip lands on an already-flipped pod
                    return {"version": wm.version, "state": "live",
                            "already": True}
                if wm.staged_version != want:
                    wm.stage(
                        want,
                        model_path=body.get("model_path"),
                        seed=body.get("seed"),
                        quantization=body.get("quantization"))
            mode = (body.get("mode")
                    or os.environ.get(ROLLOUT_DRAIN_MODE_ENV, "finish")
                    or "finish").lower()
            if mode not in ("finish", "handoff"):
                raise proto.BadRequest(
                    f"mode {mode!r} not in ('finish', 'handoff')")
            if mode == "handoff" and eng.num_active:
                return self._flip_with_handoff(wm)
            return wm.flip(mode="finish")
        if action == "rollback":
            if wm.previous_version is None and wm.staged_version:
                # the pod never flipped (stage resident / flip armed):
                # dropping the staged tree IS the rollback — admissions
                # reopen and the original version keeps serving
                wm.abort_stage()
                return {"version": wm.version, "state": "rolled_back",
                        "rolled_back": None}
            return wm.rollback()
        if action == "commit":
            return wm.commit()
        if action == "abort":
            return {"aborted": wm.abort_stage(), "version": wm.version}
        raise proto.BadRequest(
            f"action {action!r} not in (status, stage, flip, stage_flip, "
            "rollback, commit, abort)")

    def _flip_with_handoff(self, wm) -> Dict[str, Any]:
        """Handoff-mode flip: journaled in-flight streams push their seams
        to the frontend (which resumes them on a peer still serving the
        old version — the HA plane's normal continuation path) and the
        pointer flips the moment the engine empties. Unlike drain, the
        worker STAYS in service: admission never closes, the handoff flag
        clears, and post-flip requests land on the new version here."""
        eng = self.engine
        self.drain_handoff.set()
        deadline = time.monotonic() + ROLLOUT_HANDOFF_GRACE_S
        try:
            while time.monotonic() < deadline and eng.num_active:
                time.sleep(0.05)
        finally:
            self.drain_handoff.clear()
        if eng.num_active:
            # non-journaled stragglers: never flip under them — fall back
            # to the armed finish flip (they complete on the old version)
            eng.flight.note("rollout_handoff_stragglers",
                            active=eng.num_active)
            return wm.flip(mode="finish")
        return wm.flip(mode="now")

    def close(self):
        if self.kv_source is not None:
            self.kv_source.close()
        if self.kvbm_source is not None:
            self.kvbm_source.close()
        self.service.close()

    def start_generation(self, rid, prompt_ids, params, index: int = 0,
                         trace_span=None, deadline=None) -> "GenerationHandle":
        return GenerationHandle(self, rid, prompt_ids, params, index=index,
                                trace_span=trace_span, deadline=deadline)

    def start_choices(self, rid, prompt_ids, params,
                      trace_span=None,
                      deadline=None) -> List["GenerationHandle"]:
        """Submit all n choices of a request (choice i streams under
        request_id '<rid>-i'). Submission is all-or-nothing: a rejection on
        choice k aborts choices 0..k-1 before re-raising."""
        n = params.get("n", 1)
        handles: List[GenerationHandle] = []
        try:
            for i in range(n):
                handles.append(GenerationHandle(
                    self, f"{rid}-{i}" if n > 1 else rid,
                    prompt_ids, params, index=i, trace_span=trace_span,
                    deadline=deadline,
                ))
        except Exception:
            for h in handles:
                self.service.abort(h.rid)
            raise
        return handles


def run_choices(handles: List["GenerationHandle"], emit_for) -> List[tuple]:
    """Drive n choice streams concurrently; emit_for(handle) returns that
    choice's emit callback (already thread-safe). Returns the per-choice
    (text, finish_reason, completion_tokens) in choice order; the first
    choice failure propagates after all threads settle."""
    if len(handles) == 1:
        return [handles[0].run(emit_for(handles[0]))]
    results: List[Optional[tuple]] = [None] * len(handles)
    errors: List[Optional[BaseException]] = [None] * len(handles)

    def drive(i: int):
        try:
            results[i] = handles[i].run(emit_for(handles[i]))
        except BaseException as e:  # noqa: BLE001 — reported to the client
            errors[i] = e

    threads = [threading.Thread(target=drive, args=(i,), daemon=True)
               for i in range(len(handles))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for e in errors:
        if e is not None:
            raise e
    return results  # type: ignore[return-value]


class _Handler(JsonHTTPHandler):
    ctx: ServingContext  # bound by make_server
    _span = obs_tracing.NOOP_SPAN  # set per-request in do_POST

    # ------------------------------------------------------------- routes --
    def _model_ids(self) -> List[str]:
        """Served model ids: the base plus one '<base>:<adapter>' entry per
        host-registered adapter (multi-LoRA addressing)."""
        ids = [self.ctx.served_model]
        lora = self.ctx.engine.lora
        if lora is not None:
            ids += [f"{self.ctx.served_model}:{n}" for n in lora.names()]
        return ids

    def do_GET(self):
        path = self.path.split("?")[0]
        if path == "/v1/models":
            self._json(200, proto.models_response(self._model_ids()))
        elif path.startswith("/v1/models/"):
            mid = path[len("/v1/models/"):]
            if mid in self._model_ids():
                self._json(200, proto.model_response(mid))
            else:
                self._error(404, f"model {mid!r} not found", "not_found")
        elif path == "/v1/adapters":
            lora = self.ctx.engine.lora
            if lora is None:
                self._error(400, "this worker serves no adapters "
                            "(--lora-slots is 0)")
                return
            st = lora.stats()
            self._json(200, {
                "object": "list",
                "data": lora.describe(),
                "slots": {"total": st["slots_total"],
                          "free": st["slots_free"]},
            })
        elif path == "/metrics":
            self.ctx.preempt_gauge.set(
                self.ctx.engine.metrics.num_preempted)
            if self.ctx.lora_loaded_gauge is not None:
                self.ctx.lora_loaded_gauge.set(
                    len(self.ctx.engine.lora.resident()))
            if self.ctx.engine.kvbm is not None:
                pool = self.ctx.engine.kvbm.pool.stats()
                self.ctx.kvbm_blocks_gauge.set(pool["used_blocks"],
                                               state="used")
                self.ctx.kvbm_blocks_gauge.set(pool["capacity_blocks"],
                                               state="capacity")
            ds = self.ctx.kv_device_source
            if ds is not None:
                # scrape-time refresh: leaked > 0 flags a decode peer that
                # stages and crashes before pulling (HBM pinned until
                # /disagg/release) — alertable without log spelunking
                live, leaked = ds.counts()  # one lock/sweep: no double count
                self.ctx.staged_kv_gauge.set(live, state="staged")
                self.ctx.staged_kv_gauge.set(leaked, state="leaked")
            self.ctx.slo.refresh_gauges()
            self.ctx.engine_bridge.refresh()  # live MFU/MBU + warmup gauges
            self.ctx.memory_bridge.refresh()  # KV-pool/tier/tenant bytes
            self.ctx.refresh_weight_gauge()  # active weight version label
            self.ctx.health_gauge.set(  # watchdog health state machine
                self.ctx.engine.watchdog.health_code)
            body, ctype = self.ctx.metrics.registry.scrape(
                self.headers.get("Accept"))
            self._raw(200, body, ctype)
        elif path == "/live":
            # liveness stays 200 through suspect/resurrecting — killing
            # the pod mid-resurrection would turn every recoverable trip
            # into a full replacement. Quarantine is the operator's cue
            # to replace, and that rides readiness, not liveness.
            self._json(200, {"status": "ok", "uptime_s": round(
                time.time() - self.ctx.start_time, 1)})
        elif path in ("/health", "/ready"):
            wd = self.ctx.engine.watchdog
            if not wd.ok_for_traffic:
                # the quarantine invariant: a worker that cannot prove
                # progress is provably out of rotation — readiness 503
                # pulls it from k8s endpoints and the router's breakers
                self._error(503, f"engine {wd.health}",
                            "service_unavailable",
                            headers={"Retry-After": "5"})
                return
            self._json(200, {"status": "ok", "uptime_s": round(
                time.time() - self.ctx.start_time, 1)})
        elif path == "/debug/spans":
            from urllib.parse import parse_qs, urlparse

            qs = parse_qs(urlparse(self.path).query)
            self._json(200, obs_tracing.spans_debug_payload(
                qs, self.ctx.tracer.collector))
        elif path == "/debug/slo":
            from urllib.parse import parse_qs, urlparse

            qs = parse_qs(urlparse(self.path).query)
            self._json(200, obs_slo.debug_slo_payload(self.ctx.slo, qs))
        elif path == "/internal/faults":
            self._json(200, faults.http_payload())
        elif path == "/debug/trace":
            from urllib.parse import parse_qs, urlparse

            qs = parse_qs(urlparse(self.path).query)
            try:
                dur = float((qs.get("duration_s") or ["1.0"])[0])
            except ValueError:
                self._error(400, "duration_s must be a number")
                return
            try:
                data = self.ctx.capture_trace(dur)
            except TraceBusy as e:
                # another capture holds the profiler (they sleep up to
                # 30s); tell the client when to come back instead of
                # parking this thread on the lock
                self._error(409, str(e), "conflict",
                            headers={"Retry-After": str(int(dur) + 1)})
                return
            except Exception as e:
                log.exception("trace capture failed")
                self._error(503, f"trace capture failed: {e}",
                            "service_unavailable")
                return
            self._raw(200, data, "application/zip")
        elif path in ("/debug", "/debug/"):
            self._json(200, {"endpoints": WORKER_DEBUG_INDEX})
        elif path == "/debug/flight":
            from urllib.parse import parse_qs, urlparse

            from dynamo_tpu.observability.flight import debug_flight_payload

            qs = parse_qs(urlparse(self.path).query)
            self._json(200, debug_flight_payload(
                self.ctx.engine.flight, qs))
        elif path == "/debug/timeline":
            from urllib.parse import parse_qs, urlparse

            from dynamo_tpu.observability.timeline import (
                timeline_debug_payload,
            )

            qs = parse_qs(urlparse(self.path).query)
            self._json(200, timeline_debug_payload(
                self.ctx.engine.timeline, qs,
                collector=self.ctx.tracer.collector))
        elif path == "/debug/costs":
            self._json(200, self.ctx.engine.cost.rollup())
        elif path == "/worker/stats":
            import dataclasses

            eng = self.ctx.engine
            out = {
                "model": self.ctx.served_model,
                "active_seqs": eng.num_active,
                "pending": len(eng.pending),
                "free_pages": eng.allocator.free_pages,
                "total_pages": eng.cfg.num_pages,
                "max_num_seqs": eng.cfg.max_num_seqs,
                "disaggregation_mode": eng.cfg.disaggregation_mode,
                # watchdog health state machine + trip/sentinel counters
                # (the same summary the heartbeat carries to frontends)
                "health": eng.watchdog.summary(),
                # the full effective EngineConfig: profiles, engine-config
                # files, and CLI flags all merge before the engine starts,
                # so operators need the RESOLVED values, not the manifest
                "config": dataclasses.asdict(eng.cfg),
                "metrics": eng.metrics.snapshot(),
            }
            if eng.cfg.speculative_mode != "off":
                # speculation health at a glance: acceptance_rate is
                # accepted/draft (the knob docs/perf.md "Speculative
                # decoding v2" tunes K against), mean_accept_len the
                # per-window histogram mean
                m = eng.metrics
                out["spec"] = {
                    "mode": eng.cfg.speculative_mode,
                    "drafter": eng.drafter_name,
                    "num_speculative_tokens": eng.cfg.num_speculative_tokens,
                    "ngram_lookup": eng.cfg.ngram_lookup,
                    "draft_tokens": m.spec_draft_tokens,
                    "accepted_tokens": m.spec_accepted_tokens,
                    "acceptance_rate": (
                        round(m.spec_accepted_tokens / m.spec_draft_tokens, 4)
                        if m.spec_draft_tokens else 0.0),
                    "mean_accept_len": (
                        round(m.spec_accept_sum / m.spec_accept_count, 4)
                        if m.spec_accept_count else 0.0),
                    # Speculation v3: per-drafter acceptance (the drafter
                    # label of the dynamo_engine_spec_* series), the
                    # draft engine's pool/rollback books, and the
                    # adaptive-K controller's live per-slot windows
                    "by_drafter": {
                        d: {
                            "draft_tokens": m.spec_draft_by.get(d, 0),
                            "accepted_tokens": m.spec_accepted_by.get(d, 0),
                            "acceptance_rate": (
                                round(m.spec_accepted_by.get(d, 0)
                                      / m.spec_draft_by[d], 4)
                                if m.spec_draft_by.get(d) else 0.0),
                        }
                        for d in sorted(set(m.spec_draft_by)
                                        | set(m.spec_count_by))},
                }
                if eng.draft is not None:
                    out["spec"]["draft_engine"] = eng.draft.stats()
                if eng._adaptive is not None:
                    out["spec"]["adaptive_k"] = {
                        "k_max": eng._adaptive.k_max,
                        "slots": eng._adaptive.snapshot(),
                    }
            # live elasticity: active/staged/previous weight versions and
            # the double-buffer bytes (what the rollout controller polls)
            out["weights"] = eng.weights.stats()
            pc = getattr(eng, "prefix_cache", None)
            if pc is not None:
                out["prefix_cache"] = pc.stats()
            if eng.lora is not None:
                out["lora"] = eng.lora.stats()
            if eng.qos is not None:
                # per-tenant QoS: budget balances, token totals, and the
                # defer/preempt counters the isolation tests assert on
                out["qos"] = eng.qos.stats()
            if eng.kvbm is not None:
                out["kvbm"] = eng.kvbm.stats()
                if self.ctx.kvbm_source is not None:
                    out["kvbm"]["peer_port"] = self.ctx.kvbm_source.port
                if self.ctx.kv_event_publisher is not None:
                    out["kvbm"]["events"] = (
                        self.ctx.kv_event_publisher.stats())
            dc = self.ctx.disagg_client
            if dc is not None:
                # which KV plane requests ACTUALLY used (an ici deployment
                # that degraded to dcn shows up here, not just in a log)
                out["transfer_planes"] = dict(dc.plane_counts)
            ds = self.ctx.kv_device_source
            if ds is not None:
                # stage ledger health: leaked > 0 means a decode peer is
                # staging and crashing before pull/release, pinning HBM
                live, leaked = ds.counts()
                out["staged_kv"] = {"live": live, "leaked": leaked}
            # exact KV books by tier/tenant + per-tenant cost rollup —
            # the same numbers the dynamo_memory_*/dynamo_tenant_cost_*
            # series export, in one JSON read for dynamo_top and the
            # frontend's fleet aggregation
            try:
                out["memory"] = self.ctx.memory_bridge.accountant.snapshot()
            except Exception:
                log.exception("memory snapshot failed in /worker/stats")
            out["costs"] = eng.cost.rollup()
            out["timeline"] = eng.timeline.summary()
            self._json(200, out)
        else:
            self._error(404, f"no route {path}")

    def do_POST(self):
        path = self.path.split("?")[0]
        if (self.ctx.draining.is_set()
                and path.startswith(("/v1/", "/disagg/prefill"))):
            # graceful drain: admission is OFF before anything else — a
            # 503 here is retry-safe by construction (nothing ran), and
            # the frontend fails it over to another replica. The disagg
            # stage/release routes stay up: decode peers must still
            # finish in-flight KV pulls against this worker.
            self._error(503, "worker draining; retry another replica",
                        "service_unavailable")
            return
        if (not self.ctx.engine.watchdog.ok_for_traffic
                and path.startswith(("/v1/", "/disagg/prefill"))):
            # watchdog shed: a suspect/resurrecting/quarantined engine
            # takes no new inference work. Deliberately NOT routed
            # through ctx.draining — recovery must not un-drain a worker
            # that is draining for its own reasons.
            self._error(
                503,
                f"engine {self.ctx.engine.watchdog.health}; "
                "retry another replica",
                "service_unavailable", headers={"Retry-After": "5"})
            return
        # robustness plane: read-stall / reset-after-headers fault points
        # (no-ops unless armed; control-plane routes are exempt)
        self._fault_gate()
        # request span: child of the frontend's span when a traceparent
        # arrived (HTTP header, or bridged off NATS message headers by
        # nats_plane), else a fresh root seeded by x-request-id
        span = obs_tracing.NOOP_SPAN
        self._deadline = None
        self._tenant = "default"
        if path in ("/v1/chat/completions", "/v1/completions",
                    "/disagg/prefill"):
            parent = obs_context.extract_context(self.headers)
            inbound_rid = ((self.headers.get("x-request-id") or "").strip()
                           or None)
            # per-tenant QoS: trust the frontend's resolved identity
            # (x-dynamo-tenant) when present, else resolve from the
            # client's own headers — the agg single-pod path IS the edge
            self._tenant = self.ctx.tenants.resolve(self.headers,
                                                    trusted=True)
            # the propagated deadline budget (x-deadline) keeps counting
            # down on this hop; requests arriving already-exhausted shed
            # with 504 before taking an engine slot
            self._deadline = Deadline.from_headers(self.headers)
            span = self.ctx.tracer.start_span(
                "worker.request", parent=parent, kind="server",
                trace_seed=inbound_rid,
                attributes={
                    "http.path": path,
                    "worker.mode":
                        self.ctx.engine.cfg.disaggregation_mode or "agg",
                    "deadline_s": round(self._deadline.budget_s, 3),
                    "model": self.ctx.served_model,
                    "tenant.id": self._tenant,
                })
            rid = inbound_rid or (span.trace_id if span.recording else None)
            if rid:
                self.set_request_id(rid)
        self._span = span
        try:
            try:
                if self._deadline is not None and self._deadline.expired:
                    raise TimeoutError(
                        "deadline budget exhausted before processing; "
                        "request shed")
                if path == "/v1/chat/completions":
                    self._chat(self._read_json_body())
                elif path == "/v1/completions":
                    self._completion(self._read_json_body())
                elif path == "/disagg/prefill":
                    self._disagg_prefill(self._read_json_body())
                elif path == "/disagg/stage":
                    self._disagg_stage(self._read_json_body())
                elif path == "/disagg/release":
                    self._disagg_release(self._read_json_body())
                elif path == "/v1/adapters":
                    self._adapters_post(self._read_json_body())
                elif path == "/internal/faults":
                    try:
                        self._json(200, faults.http_configure(
                            self._read_json_body()))
                    except ValueError as e:
                        raise proto.BadRequest(str(e))
                elif path == "/internal/drain":
                    # planner v2 pre-drain: the operator marks this pod a
                    # scale-down victim and asks it to start shedding /
                    # handing off BEFORE the Deployment shrink delivers
                    # SIGTERM (which runs the same, idempotent drain)
                    try:
                        body = self._read_json_body()
                    except Exception:  # noqa: BLE001 — body is optional
                        body = {}
                    self.ctx.begin_drain()
                    if body.get("handoff"):
                        self.ctx.request_handoff()
                    self._json(200, {"draining": True,
                                     "active_seqs":
                                         self.ctx.engine.num_active,
                                     "pending":
                                         len(self.ctx.engine.pending)})
                elif path == "/internal/rollout":
                    # hitless weight rollout control surface (docs/
                    # robustness.md "Hitless weight rollout"): stage /
                    # flip / rollback / commit / status. Stays reachable
                    # while draining (it is not a /v1 route) so a fleet
                    # rollback can still reach a pod mid-drain.
                    try:
                        body = self._read_json_body()
                    except Exception:  # noqa: BLE001 — body is optional
                        body = {}
                    wd = self.ctx.engine.watchdog
                    if not wd.ok_for_traffic:
                        # fail fast instead of parking this HTTP thread
                        # on a wedged engine's exec lock — the operator's
                        # tick stays bounded and retries once the
                        # resurrection (or pod replacement) lands
                        self._error(
                            503, f"engine {wd.health}; rollout refused",
                            "service_unavailable",
                            headers={"Retry-After": "5"})
                        return
                    self._json(200, self.ctx.rollout(body))
                elif path == "/internal/reclaim":
                    # spot/maintenance reclamation notice: this replica's
                    # capacity disappears in deadline_s seconds — ack
                    # immediately, drain under the hard deadline in the
                    # background (docs/robustness.md "Preemptible batch
                    # tier")
                    try:
                        body = self._read_json_body()
                    except Exception:  # noqa: BLE001 — body is optional
                        body = {}
                    qs = urllib.parse.parse_qs(
                        urllib.parse.urlsplit(self.path).query)
                    raw = (qs.get("deadline_s", [None])[0]
                           if qs.get("deadline_s")
                           else body.get("deadline_s"))
                    try:
                        deadline_s = (float(raw) if raw is not None
                                      else _env_reclaim_deadline_s())
                    except (TypeError, ValueError):
                        raise proto.BadRequest(
                            f"invalid deadline_s {raw!r}")
                    if deadline_s <= 0:
                        raise proto.BadRequest(
                            "deadline_s must be > 0")
                    self._json(200, self.ctx.reclaim(deadline_s))
                else:
                    self._error(404, f"no route {path}")
            except Exception as e:
                span.set_status("ERROR", f"{type(e).__name__}: {e}")
                raise
        except proto.BadRequest as e:
            self._fail(400, str(e))
        except OutOfPages as e:  # transient capacity: client should retry
            self._fail(503, str(e), "service_unavailable")
        except RuntimeError as e:  # disagg dependency unavailable
            self._fail(503, str(e), "service_unavailable")
        except ValueError as e:  # engine-level rejection (over-length, ...)
            self._fail(400, str(e))
        except TimeoutError as e:
            self._fail(504, str(e), "timeout")
        except Exception:
            log.exception("request failed")
            self._fail(500, "internal error", "internal_error")
        finally:
            span.end()

    def _fail(self, code: int, msg: str, etype: str = "invalid_request_error"):
        if code >= 500:
            # the worker-side error-rate SLO source (observability/slo.py);
            # 4xx are the client's problem and never burn budget
            self.ctx.metrics.errors_total.inc(
                model=self.ctx.served_model, code=str(code))
        if self.sse_started:
            self._sse_error(msg)
        else:
            self._error(code, msg, etype)

    # ------------------------------------------------------------ handlers --
    def _disagg_prefill(self, body):
        """Prefill-role RPC: run the prompt, park KV, return the bootstrap
        coordinates for the decode side's pull."""
        ctx = self.ctx
        if ctx.kv_source is None:
            raise proto.BadRequest(
                "this worker is not in --disaggregation-mode prefill"
            )
        rid = body.get("request_id")
        ids = body.get("prompt_token_ids")
        if not rid or not isinstance(ids, list) or not ids:
            raise proto.BadRequest("need request_id and prompt_token_ids")
        lp = body.get("logprobs")
        seed = body.get("seed")
        req = GenRequest(
            rid, [int(t) for t in ids],
            temperature=float(body.get("temperature", 0.0)),
            top_p=float(body.get("top_p", 1.0)),
            top_k=int(body.get("top_k", 0)),
            min_p=float(body.get("min_p", 0.0)),
            logit_bias={int(k): float(v)
                        for k, v in (body.get("logit_bias") or {}).items()}
            or None,
            seed=int(seed) if seed is not None else None,
            logprobs=int(lp) if lp is not None else None,
            guided_json=bool(body.get("guided_json", False)),
            # multi-LoRA: the decode role forwards its request's adapter so
            # the prefill runs under the same weights the decode will
            adapter=body.get("adapter") or None,
            # per-tenant QoS: the decode role forwards the resolved tenant
            # so prefill-side spans/metrics agree with the decode side
            tenant=body.get("tenant") or None,
        )
        if req.tenant:
            self._tenant = req.tenant
        else:
            req.tenant = self._tenant  # header-resolved (x-dynamo-tenant)
        self.ctx.metrics.tenant_requests.inc(tenant=self._tenant)
        self._span.set_attribute("request.id", rid)
        faults.sleep_point("worker.slow_prefill")
        if self._deadline is not None and self._deadline.expired:
            # the stall (queueing, chaos, or a slow peer) ate the whole
            # budget: shed BEFORE running a prefill nobody will pull
            raise TimeoutError(
                "deadline budget exhausted before prefill; request shed")
        t0 = time.monotonic()
        with ctx.tracer.start_span(
                "worker.prefill_only", parent=self._span,
                attributes={"request.id": rid,
                            "prompt_tokens": len(ids)}) as pspan:
            first, n_tokens, extras = ctx.engine.prefill_only(req)
            eng_ph = ctx.engine.metrics.phases
            pspan.set_attributes({
                "engine.prefill.p50_ms":
                    round(eng_ph["prefill"].quantile_ms(0.5), 3),
                "engine.prefill.p95_ms":
                    round(eng_ph["prefill"].quantile_ms(0.95), 3),
            })
        ctx.metrics.ttft.observe(
            time.monotonic() - t0,
            exemplar=(self._span.trace_id if self._span.recording else None),
            model=ctx.served_model)
        ctx.metrics.requests_total.inc(model=ctx.served_model)
        ctx.metrics.isl.observe(n_tokens, model=ctx.served_model)
        self._json(200, {
            "request_id": rid,
            "first_token": first,
            "n_tokens": n_tokens,
            "bootstrap_port": ctx.kv_source.port,
            "transfer_backend": ctx.engine.cfg.disaggregation_transfer_backend,
            # staging itself is lazy (/disagg/stage) so a TCP-pulling peer
            # never pins a gathered device copy in the transfer server
            "device_transfer": bool(ctx.kv_device_source is not None
                                    and ctx.kv_device_source.eligible),
            **extras,
        })

    def _disagg_stage(self, body):
        """Stage a parked sequence's KV with the transfer server and return
        the device-pull coordinates (called by an ici decode peer just
        before it pulls)."""
        ctx = self.ctx
        if ctx.kv_device_source is None:
            raise proto.BadRequest(
                "this worker does not serve device-buffer KV transfer")
        rid = body.get("request_id")
        if not rid:
            raise proto.BadRequest("need request_id")
        try:
            staged = ctx.kv_device_source.stage(rid)
        except KeyError:
            raise proto.BadRequest(f"unknown request {rid!r}")
        if staged is None:
            raise proto.BadRequest("device-buffer staging unavailable")
        self._json(200, {"request_id": rid, **staged})

    def _disagg_release(self, body):
        """Decode-side ack for a device-buffer KV pull: free the parked
        pages (the TCP plane acks in-stream; the TTL sweep covers peers
        that crash between pull and release)."""
        ctx = self.ctx
        if ctx.kv_source is None:
            raise proto.BadRequest(
                "this worker is not in --disaggregation-mode prefill"
            )
        rid = body.get("request_id")
        if not rid:
            raise proto.BadRequest("need request_id")
        ctx.engine.release_parked(rid)
        if ctx.kv_device_source is not None:
            # forget the staged gather too, so the stage ledger (and its
            # array refs) doesn't wait out the TTL for well-behaved peers
            ctx.kv_device_source.mark_released(rid)
        self._json(200, {"request_id": rid, "released": True})

    def _adapters_post(self, body):
        """Runtime adapter management (POST /v1/adapters):
        {"name": n, "path": p}           register (host store; device lazy)
        {"name": n, "path": p, "load": true}   register + pin into a slot
        {"name": n, "unload": true}      drop the device slot (host stays)
        {"name": n, "remove": true}      unregister entirely
        """
        from dynamo_tpu.lora.registry import NoFreeAdapterSlot

        lora = self.ctx.engine.lora
        if lora is None:
            raise proto.BadRequest(
                "this worker serves no adapters (--lora-slots is 0)")
        name = body.get("name")
        if not isinstance(name, str) or not name:
            raise proto.BadRequest("'name' is required")
        try:
            if body.get("remove"):
                lora.unregister(name)
                self._json(200, {"name": name, "removed": True})
                return
            if body.get("unload"):
                was = lora.unload(name)
                self._json(200, {"name": name, "unloaded": was})
                return
            if body.get("path"):
                lora.register(name, path=str(body["path"]))
            elif not lora.known(name):
                raise proto.BadRequest(
                    f"unknown adapter {name!r} (give 'path' to register)")
            slot = None
            if body.get("load"):
                slot = lora.acquire_slot(name)
        except NoFreeAdapterSlot as e:
            self._error(503, str(e), "service_unavailable")
            return
        except (ValueError, KeyError) as e:
            raise proto.BadRequest(str(e))
        self._json(200, {"name": name, "registered": True,
                         "resident": lora.slot_of(name) is not None,
                         **({"slot": slot} if slot is not None else {})})

    def _check_model(self, model: str) -> Optional[str]:
        """Validate the request's model id; returns the adapter name when
        the id uses '<base>:<adapter>' addressing (multi-LoRA), else None."""
        bases = (self.ctx.served_model, self.ctx.engine.cfg.model)
        if model in bases:
            return None
        adapter = None
        for b in bases:
            if model.startswith(b + ":"):
                adapter = model[len(b) + 1:]
                break
        lora = self.ctx.engine.lora
        if adapter and lora is not None and lora.known(adapter):
            return adapter
        raise proto.BadRequest(
            f"model {model!r} not served (serving {self.ctx.served_model!r}"
            + (f" + adapters {lora.names()}" if lora is not None else "")
            + ")"
        )

    # ------------------------------------------- mid-stream recovery ----
    def _journal_comment(self, obj) -> None:
        """One recovery-journal record as an SSE comment frame. Rides the
        response stream itself, so the journal dies with the connection
        exactly when the frontend stops needing it."""
        self._write_chunk(recovery.comment_frame(obj))

    def _setup_recovery(self, body, p, stream_gated: bool = False):
        """Continuation + journaling plumbing (serving/recovery.py).

        Returns (rec, journaling): `rec` is the validated inbound
        ``dynamo_recovery`` continuation (streaming only), `journaling`
        whether this stream should emit journal comments. For a journaled
        UNSEEDED sampled stream the effective seed is pinned here and
        journaled, so a continuation resumes the identical chain.
        `stream_gated` marks streams whose text is gated/buffered (auto
        tool-choice) — delivered chars there aren't a pure function of
        the token ids, so they are not journaled."""
        rec = body.get(recovery.RECOVERY_BODY_KEY)
        if rec is not None:
            try:
                rec = recovery.normalize_continuation(rec)
            except ValueError as e:
                raise proto.BadRequest(str(e))
        journaling = bool(self.headers.get(recovery.JOURNAL_HEADER)
                          and p["stream"] and p.get("n", 1) == 1
                          and not stream_gated)
        if rec is not None and p["stream"]:
            p["_recovery"] = rec
            if p["seed"] is None and rec.get("seed") is not None:
                p["seed"] = rec["seed"]
        if journaling and p["seed"] is None and p["temperature"] > 0:
            p["seed"] = random.getrandbits(31)
        return (rec if p["stream"] else None), journaling

    def _chat(self, body):
        p = proto.parse_chat_request(body)
        p["adapter"] = self._check_model(p["model"])
        p["tenant"] = self._tenant
        tools, tc = p["tools"], p["tool_choice"]
        forced_tool = isinstance(tc, tuple)  # ("function", name)
        if forced_tool:
            if p["stream"]:
                raise proto.BadRequest(
                    "streaming is not supported with a forced tool_choice")
            # the forced call's arguments are produced by the JSON-guided
            # decoder: one complete JSON object
            p["guided_json"] = True
        prompt_text = self.ctx.tokenizer.apply_chat_template(
            p["messages"], tools=tools if tc != "none" else None)
        prompt_ids = self.ctx.tokenizer.encode(prompt_text)
        # KV event plane: associate this request's token-block chain with
        # the canonical text the frontend's router hashed (json.dumps of
        # the messages — serving/frontend.py builds the same string)
        import json as _json

        self.ctx.register_kv_route(prompt_ids, _json.dumps(p["messages"]))
        # a recovery continuation reuses the ORIGINAL response id so the
        # spliced stream's chunks stay self-consistent for the client
        rec, journaling = self._setup_recovery(
            body, p, stream_gated=(tools is not None and tc == "auto"))
        rid = (rec or {}).get("response_id") or proto.new_id("chatcmpl")
        self._span.set_attribute("request.id", rid)
        handles = self.ctx.start_choices(  # may raise -> 400
            rid, prompt_ids, p, trace_span=self._span,
            deadline=self._deadline)

        if p["stream"]:
            with_null = p.get("include_usage", False)
            self._start_sse()
            lock = threading.Lock()
            if journaling:
                handles[0].journal_sink = self._journal_comment
                self._journal_comment(
                    {"start": {"id": rid, "seed": p.get("seed")}})
            if rec is None or not rec.get("role_sent"):
                # a continuation skips the role preamble when the
                # original stream already delivered it
                for h in handles:
                    self._sse_chunk(
                        proto.chat_chunk(rid, p["model"],
                                         {"role": "assistant"},
                                         None, with_usage_null=with_null,
                                         index=h.index)
                    )

            # tool_choice "auto": gate each choice's stream so a leading
            # '{' buffers until finish and can become ONE tool_calls
            # delta; anything else streams as before
            gating = tools is not None and tc == "auto"

            def emit_for(h):
                gate = proto.AutoToolStreamGate() if gating else None

                def emit(delta, finish, lp_entry) -> bool:
                    with lock:
                        ok = True
                        entries = ([lp_entry] if lp_entry is not None
                                   else [])
                        if gate is not None:
                            delta, entries = gate.feed(delta, lp_entry)
                            if finish is not None:
                                call, held, held_lp = gate.finish(tools, tc)
                                if call is not None:
                                    finish = "tool_calls"
                                    ok = self._sse_chunk(proto.chat_chunk(
                                        rid, p["model"],
                                        proto.tool_call_chunk_delta(call),
                                        None, with_usage_null=with_null,
                                        index=h.index)) and ok
                                else:
                                    delta += held
                                    entries = entries + held_lp
                        if delta or entries:
                            ok = self._sse_chunk(proto.chat_chunk(
                                rid, p["model"], {"content": delta}, None,
                                with_usage_null=with_null, index=h.index,
                                logprob_entries=(
                                    entries if entries
                                    else (None if not h.want_logprobs else [])
                                ),
                            )) and ok
                        if finish is not None:
                            ok = self._sse_chunk(proto.chat_chunk(
                                rid, p["model"], {}, finish,
                                with_usage_null=with_null, index=h.index,
                            )) and ok
                        return ok
                return emit

            results = run_choices(handles, emit_for)
            if any(r[1] == "handoff" for r in results):
                # active drain handoff: end the chunked body WITHOUT
                # [DONE] — the frontend relay reads that as a mid-stream
                # failure and splices the journaled continuation
                self._end_sse()
                return
            if p.get("include_usage"):
                # usage describes the LOGICAL request: original prompt
                # length, and completion tokens across the recovery seam
                self._sse_chunk(proto.usage_chunk(
                    rid, p["model"], "chat.completion.chunk",
                    len(prompt_ids),
                    sum(r[2] for r in results)
                    + sum(h.prior_count for h in handles),
                ))
            self._sse_chunk("[DONE]")
            self._end_sse()
        else:
            results = run_choices(handles,
                                  lambda h: (lambda d, f, lp: True))

            def tool_call_for(text, finish):
                # forced: only a stop-finished object is a candidate (a
                # length cutoff stays honest text), and extract_tool_call
                # re-validates the JSON so a user stop-string truncation
                # can never ship unparseable arguments
                if tc == "none" or tools is None:
                    return None
                if forced_tool and finish != "stop":
                    return None
                return proto.extract_tool_call(text, tools, tc)

            choices = [
                proto.chat_choice(
                    h.index, text, finish,
                    h.lp_entries if h.want_logprobs else None,
                    tool_call=tool_call_for(text, finish),
                )
                for h, (text, finish, _) in zip(handles, results)
            ]
            self._json(
                200,
                proto.chat_completion_response(
                    rid, p["model"], choices, len(prompt_ids),
                    sum(r[2] for r in results),
                ),
            )

    def _completion(self, body):
        p = proto.parse_completion_request(body)
        p["adapter"] = self._check_model(p["model"])
        p["tenant"] = self._tenant
        prompt_ids = self.ctx.tokenizer.encode(p["prompt"])
        # KV event plane: the frontend routes completions on the raw
        # prompt string — the same canonical text registered here
        self.ctx.register_kv_route(prompt_ids, p["prompt"])
        rec, journaling = self._setup_recovery(body, p)
        rid = (rec or {}).get("response_id") or proto.new_id("cmpl")
        self._span.set_attribute("request.id", rid)
        handles = self.ctx.start_choices(rid, prompt_ids, p,
                                         trace_span=self._span,
                                         deadline=self._deadline)

        def lp_block(h):
            if not h.want_logprobs:
                return None
            return proto.completion_logprobs(
                [e["token"] for e in h.lp_entries],
                [e["logprob"] for e in h.lp_entries],
                [[(a["token"], a["logprob"]) for a in e["top_logprobs"]]
                 for e in h.lp_entries],
            )

        if p["stream"]:
            self._start_sse()
            lock = threading.Lock()
            if journaling:
                handles[0].journal_sink = self._journal_comment
                self._journal_comment(
                    {"start": {"id": rid, "seed": p.get("seed")}})

            def emit_for(h):
                def emit(delta, finish, lp_entry) -> bool:
                    if not (delta or finish is not None
                            or lp_entry is not None):
                        return True
                    with lock:
                        choice = {"index": h.index, "text": delta,
                                  "finish_reason": finish}
                        if lp_entry is not None:
                            choice["logprobs"] = proto.completion_logprobs(
                                [lp_entry["token"]], [lp_entry["logprob"]],
                                [[(a["token"], a["logprob"])
                                  for a in lp_entry["top_logprobs"]]],
                            )
                        chunk = {
                            "id": rid, "object": "text_completion",
                            "created": int(time.time()), "model": p["model"],
                            "choices": [choice],
                        }
                        if p.get("include_usage"):
                            chunk["usage"] = None
                        return self._sse_chunk(chunk)
                return emit

            results = run_choices(handles, emit_for)
            if any(r[1] == "handoff" for r in results):
                # drain handoff: no [DONE] — the frontend splices on
                self._end_sse()
                return
            if p.get("include_usage"):
                self._sse_chunk(proto.usage_chunk(
                    rid, p["model"], "text_completion", len(prompt_ids),
                    sum(r[2] for r in results)
                    + sum(h.prior_count for h in handles),
                ))
            self._sse_chunk("[DONE]")
            self._end_sse()
        else:
            results = run_choices(handles,
                                  lambda h: (lambda d, f, lp: True))
            choices = [
                proto.completion_choice(h.index, text, finish, lp_block(h))
                for h, (text, finish, _) in zip(handles, results)
            ]
            self._json(
                200,
                proto.completion_response(
                    rid, p["model"], choices, len(prompt_ids),
                    sum(r[2] for r in results),
                ),
            )


def make_server(ctx: ServingContext, host: str = "0.0.0.0", port: int = 8000):
    return make_http_server(_Handler, {"ctx": ctx}, host, port)
