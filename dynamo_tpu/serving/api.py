"""OpenAI-compatible HTTP server serving a local Engine — the aggregated-worker
path, equivalent to the reference's engine worker + frontend collapsed into one
pod (/root/reference/examples/deploy/vllm/agg.yaml).

Endpoints: GET /v1/models, POST /v1/chat/completions, POST /v1/completions
(both with SSE streaming), GET /metrics (Prometheus), GET /health, /live,
/ready, GET /worker/stats (router introspection).
"""

from __future__ import annotations

import logging
import time
from typing import List, Optional

from dynamo_tpu.engine.engine import Engine
from dynamo_tpu.engine.kv_cache import OutOfPages
from dynamo_tpu.engine.request import GenRequest
from dynamo_tpu.engine.tokenizer import get_tokenizer
from dynamo_tpu.serving import protocol as proto
from dynamo_tpu.serving.engine_service import EngineService
from dynamo_tpu.serving.http_base import (
    JsonHTTPHandler,
    make_http_server,
    serve_forever_in_thread,  # noqa: F401  (re-export for callers/tests)
)
from dynamo_tpu.serving.metrics import FrontendMetrics, Gauge

log = logging.getLogger("dynamo_tpu.api")


class IncrementalDetokenizer:
    """Streaming detokenization with bounded re-decode (vLLM-style windows):
    each push decodes only the tokens since the last emitted boundary, holding
    back trailing bytes that don't yet form complete UTF-8."""

    def __init__(self, tokenizer):
        self.tok = tokenizer
        self.ids: List[int] = []
        self.prefix_offset = 0
        self.read_offset = 0
        self.emitted = ""

    def push(self, token_id: int) -> str:
        self.ids.append(token_id)
        prefix_text = self.tok.decode(self.ids[self.prefix_offset:self.read_offset])
        new_text = self.tok.decode(self.ids[self.prefix_offset:])
        if new_text.endswith("�"):
            return ""
        delta = new_text[len(prefix_text):]
        self.prefix_offset = self.read_offset
        self.read_offset = len(self.ids)
        self.emitted += delta
        return delta


class GenerationHandle:
    """A submitted request plus its event stream — submission (and its
    validation errors) happens strictly before any response bytes."""

    def __init__(self, ctx: "ServingContext", rid: str, prompt_ids: List[int],
                 params: dict):
        self.ctx = ctx
        self.rid = rid
        self.prompt_ids = prompt_ids
        self.req = GenRequest(
            rid,
            list(prompt_ids),
            max_tokens=params["max_tokens"],
            temperature=params["temperature"],
            top_p=params["top_p"],
            top_k=params["top_k"],
            ignore_eos=params.get("ignore_eos", False),
        )
        if ctx.disagg_client is not None:
            # decode role: prefill remotely, pull KV, continue locally
            self.queue = ctx.disagg_client.start(self.req)
        else:
            self.queue = ctx.service.submit(self.req)  # raises ValueError early
        ctx.metrics.requests_total.inc(model=ctx.served_model)
        ctx.metrics.isl.observe(len(prompt_ids), model=ctx.served_model)

    def run(self, emit) -> tuple:
        """Drive the stream; emit(delta, finish|None) -> bool keeps going while
        True. A False return (client gone) aborts the engine request.

        Returns (text, finish_reason, completion_tokens)."""
        ctx, m = self.ctx, self.ctx.metrics
        model = ctx.served_model
        t0 = time.monotonic()
        t_prev: Optional[float] = None
        detok = IncrementalDetokenizer(ctx.tokenizer)
        n_out = 0
        finish = "stop"
        for ev in ctx.service.drain(self.req, self.queue):
            now = time.monotonic()
            if t_prev is None:
                m.ttft.observe(now - t0, model=model)
            else:
                m.itl.observe(now - t_prev, model=model)
            t_prev = now
            delta = ""
            if ev.token_id >= 0:
                n_out += 1
                delta = detok.push(ev.token_id)
            fr = proto.map_finish_reason(ev.finish_reason) if ev.finished else None
            if ev.finished:
                finish = fr or "stop"
            if delta or ev.finished:
                if not emit(delta, fr) and not ev.finished:
                    log.info("client disconnected; aborting %s", self.rid)
                    ctx.service.abort(self.rid)
                    finish = "abort"
                    break
        m.duration.observe(time.monotonic() - t0, model=model)
        m.osl.observe(n_out, model=model)
        ctx.kv_gauge.set(ctx.engine.allocator.free_pages)
        return detok.emitted, finish, n_out


class ServingContext:
    """Everything the request handlers need, bundled for the handler class."""

    def __init__(self, engine: Engine, served_model: str,
                 prefill_urls=None, frontend_url=None):
        self.engine = engine
        self.service = EngineService(engine)
        self.served_model = served_model
        self.tokenizer = get_tokenizer(engine.cfg.model, engine.cfg.model_path)
        self.metrics = FrontendMetrics()
        self.kv_gauge = Gauge(
            "dynamo_worker_kv_free_pages", "Free KV pages", self.metrics.registry
        )
        self.start_time = time.time()

        # --- disaggregation wiring (mirrors the reference's role flags,
        # /root/reference/examples/deploy/sglang/disagg.yaml:45-52) ---
        self.kv_source = None
        self.disagg_client = None
        mode = engine.cfg.disaggregation_mode
        if mode == "prefill":
            from dynamo_tpu.transfer.kv_transfer import KVSource

            self.kv_source = KVSource(
                engine, port=engine.cfg.disaggregation_bootstrap_port
            )
            log.info("prefill role: KV bootstrap on port %d", self.kv_source.port)
        elif mode == "decode":
            from dynamo_tpu.serving.disagg import DisaggDecodeClient, PrefillPool

            self.disagg_client = DisaggDecodeClient(
                self, PrefillPool(prefill_urls, frontend_url)
            )

    def close(self):
        if self.kv_source is not None:
            self.kv_source.close()
        self.service.close()

    def start_generation(self, rid, prompt_ids, params) -> "GenerationHandle":
        return GenerationHandle(self, rid, prompt_ids, params)


class _Handler(JsonHTTPHandler):
    ctx: ServingContext  # bound by make_server

    # ------------------------------------------------------------- routes --
    def do_GET(self):
        path = self.path.split("?")[0]
        if path == "/v1/models":
            self._json(200, proto.models_response([self.ctx.served_model]))
        elif path == "/metrics":
            self._raw(200, self.ctx.metrics.registry.expose().encode(),
                      "text/plain; version=0.0.4")
        elif path in ("/health", "/live", "/ready"):
            self._json(200, {"status": "ok", "uptime_s": round(
                time.time() - self.ctx.start_time, 1)})
        elif path == "/worker/stats":
            eng = self.ctx.engine
            self._json(200, {
                "model": self.ctx.served_model,
                "active_seqs": eng.num_active,
                "pending": len(eng.pending),
                "free_pages": eng.allocator.free_pages,
                "total_pages": eng.cfg.num_pages,
                "max_num_seqs": eng.cfg.max_num_seqs,
                "disaggregation_mode": eng.cfg.disaggregation_mode,
                "metrics": eng.metrics.snapshot(),
            })
        else:
            self._error(404, f"no route {path}")

    def do_POST(self):
        path = self.path.split("?")[0]
        try:
            if path == "/v1/chat/completions":
                self._chat(self._read_json_body())
            elif path == "/v1/completions":
                self._completion(self._read_json_body())
            elif path == "/disagg/prefill":
                self._disagg_prefill(self._read_json_body())
            else:
                self._error(404, f"no route {path}")
        except proto.BadRequest as e:
            self._fail(400, str(e))
        except OutOfPages as e:  # transient capacity: client should retry
            self._fail(503, str(e), "service_unavailable")
        except RuntimeError as e:  # disagg dependency unavailable
            self._fail(503, str(e), "service_unavailable")
        except ValueError as e:  # engine-level rejection (over-length, ...)
            self._fail(400, str(e))
        except TimeoutError as e:
            self._fail(504, str(e), "timeout")
        except Exception:
            log.exception("request failed")
            self._fail(500, "internal error", "internal_error")

    def _fail(self, code: int, msg: str, etype: str = "invalid_request_error"):
        if self.sse_started:
            self._sse_error(msg)
        else:
            self._error(code, msg, etype)

    # ------------------------------------------------------------ handlers --
    def _disagg_prefill(self, body):
        """Prefill-role RPC: run the prompt, park KV, return the bootstrap
        coordinates for the decode side's pull."""
        ctx = self.ctx
        if ctx.kv_source is None:
            raise proto.BadRequest(
                "this worker is not in --disaggregation-mode prefill"
            )
        rid = body.get("request_id")
        ids = body.get("prompt_token_ids")
        if not rid or not isinstance(ids, list) or not ids:
            raise proto.BadRequest("need request_id and prompt_token_ids")
        req = GenRequest(
            rid, [int(t) for t in ids],
            temperature=float(body.get("temperature", 0.0)),
            top_p=float(body.get("top_p", 1.0)),
            top_k=int(body.get("top_k", 0)),
        )
        t0 = time.monotonic()
        first, n_tokens = ctx.engine.prefill_only(req)
        ctx.metrics.ttft.observe(time.monotonic() - t0, model=ctx.served_model)
        ctx.metrics.requests_total.inc(model=ctx.served_model)
        ctx.metrics.isl.observe(n_tokens, model=ctx.served_model)
        self._json(200, {
            "request_id": rid,
            "first_token": first,
            "n_tokens": n_tokens,
            "bootstrap_port": ctx.kv_source.port,
            "transfer_backend": ctx.engine.cfg.disaggregation_transfer_backend,
        })

    def _check_model(self, model: str):
        if model not in (self.ctx.served_model, self.ctx.engine.cfg.model):
            raise proto.BadRequest(
                f"model {model!r} not served (serving {self.ctx.served_model!r})"
            )

    def _chat(self, body):
        p = proto.parse_chat_request(body)
        self._check_model(p["model"])
        prompt_text = self.ctx.tokenizer.apply_chat_template(p["messages"])
        prompt_ids = self.ctx.tokenizer.encode(prompt_text)
        rid = proto.new_id("chatcmpl")
        gen = self.ctx.start_generation(rid, prompt_ids, p)  # may raise -> 400

        if p["stream"]:
            with_null = p.get("include_usage", False)
            self._start_sse()
            self._sse_chunk(
                proto.chat_chunk(rid, p["model"], {"role": "assistant"}, None,
                                 with_usage_null=with_null)
            )

            def emit(delta, finish) -> bool:
                ok = True
                if delta:
                    ok = self._sse_chunk(
                        proto.chat_chunk(rid, p["model"], {"content": delta},
                                         None, with_usage_null=with_null)
                    )
                if finish is not None:
                    ok = self._sse_chunk(
                        proto.chat_chunk(rid, p["model"], {}, finish,
                                         with_usage_null=with_null)) and ok
                return ok

            _, _, n_out = gen.run(emit)
            if p.get("include_usage"):
                self._sse_chunk(proto.usage_chunk(
                    rid, p["model"], "chat.completion.chunk",
                    len(prompt_ids), n_out,
                ))
            self._sse_chunk("[DONE]")
            self._end_sse()
        else:
            text, finish, n_out = gen.run(lambda d, f: True)
            self._json(
                200,
                proto.chat_completion_response(
                    rid, p["model"], text, finish, len(prompt_ids), n_out
                ),
            )

    def _completion(self, body):
        p = proto.parse_completion_request(body)
        self._check_model(p["model"])
        prompt_ids = self.ctx.tokenizer.encode(p["prompt"])
        rid = proto.new_id("cmpl")
        gen = self.ctx.start_generation(rid, prompt_ids, p)
        if p["stream"]:
            self._start_sse()

            def emit(delta, finish) -> bool:
                if delta or finish is not None:
                    chunk = {
                        "id": rid, "object": "text_completion",
                        "created": int(time.time()), "model": p["model"],
                        "choices": [{"index": 0, "text": delta,
                                     "finish_reason": finish}],
                    }
                    if p.get("include_usage"):
                        chunk["usage"] = None
                    return self._sse_chunk(chunk)
                return True

            _, _, n_out = gen.run(emit)
            if p.get("include_usage"):
                self._sse_chunk(proto.usage_chunk(
                    rid, p["model"], "text_completion", len(prompt_ids), n_out,
                ))
            self._sse_chunk("[DONE]")
            self._end_sse()
        else:
            text, finish, n_out = gen.run(lambda d, f: True)
            self._json(
                200,
                proto.completion_response(
                    rid, p["model"], text, finish, len(prompt_ids), n_out
                ),
            )


def make_server(ctx: ServingContext, host: str = "0.0.0.0", port: int = 8000):
    return make_http_server(_Handler, {"ctx": ctx}, host, port)
