"""Engine-worker process entrypoint, specialised per backend profile.

The reference deploys three engine backends (vLLM / SGLang / TRT-LLM) that
share one serving contract but differ in scheduling philosophy. This repo
mirrors that as one TPU engine core specialised by three **backend
profiles** — each `python -m dynamo_tpu.<backend>` entrypoint selects a
distinct set of scheduling defaults (explicit CLI flags always win):

- ``jetstream``  — orchestrated serving: fixed fused decode windows driven
  synchronously (JetStream's orchestrator model); no chunked prefill —
  admission happens between windows.
- ``vllm_tpu``   — continuous batching: chunked prefill interleaved with
  decode, automatic prefix caching, async (overlapped) scheduling —
  vLLM's scheduler model.
- ``trtllm_tpu`` — the compiled-engine model: an explicit per-role
  ``--engine-config`` file is REQUIRED (TRT-LLM's engine_configs analogue,
  /root/reference/examples/dgdr/trtllm/disagg.yaml:39-40,64-65), AOT
  warmup always runs before /ready, and compiled programs persist in an
  engine cache directory (the TRT engine-build analogue).

CLI contract mirrors the reference's worker invocations
(`python3 -m dynamo.vllm --model ...`,
/root/reference/examples/deploy/vllm/agg.yaml:29-35; disagg role flags per
/root/reference/examples/deploy/vllm/disagg.yaml:37,57 and
/root/reference/examples/deploy/sglang/disagg.yaml:45-52), plus
`--frontend-url` for heartbeat registration with the frontend/router.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import socket
import threading
import time
import urllib.request

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import Engine
from dynamo_tpu.serving.api import ServingContext, make_server

log = logging.getLogger("dynamo_tpu.worker")

# Per-backend scheduling defaults (see module docstring). Applied as argparse
# defaults, so an explicit CLI flag always overrides its profile value.
# --speculative-mode is deliberately NOT a profile default: it is a
# workload bet (docs/perf.md "Speculative decoding v2" — pays off only on
# repetitive/agentic token streams), so the operator opts in per
# deployment; v2 composes with every profile here, including the
# chunked/mixed continuous-batching ones. Acceptance health lands on this
# worker's /metrics (dynamo_engine_spec_*) and /worker/stats `spec`.
BACKEND_PROFILES = {
    "jetstream": dict(
        num_scheduler_steps=8,
        async_scheduling=False,
        prefill_chunk_tokens=0,
        enable_prefix_caching=False,
    ),
    "vllm_tpu": dict(
        num_scheduler_steps=1,
        async_scheduling=True,
        prefill_chunk_tokens=256,
        enable_prefix_caching=True,
    ),
    "trtllm_tpu": dict(
        num_scheduler_steps=4,
        async_scheduling=True,
        prefill_chunk_tokens=256,
        enable_prefix_caching=True,
    ),
}


def _self_url(host: str, port: int) -> str:
    if host not in ("0.0.0.0", "::"):
        return f"http://{host}:{port}"
    # advertise the pod/host IP (downward-API env in K8s, hostname locally)
    adv = os.environ.get("POD_IP") or socket.gethostbyname(socket.gethostname())
    return f"http://{adv}:{port}"


def heartbeat_loop(ctx: ServingContext, frontend_url: str, self_url: str,
                   interval: float, stop: threading.Event):
    # HA frontend plane: --frontend-url may name N replicas
    # (comma-separated). The worker heartbeats to EVERY one so each
    # replica's registry is complete on its own — no replica depends on
    # another being alive to know this worker exists.
    payload_urls = [u.strip().rstrip("/") + "/internal/register"
                    for u in frontend_url.split(",") if u.strip()]
    first = True
    while True:
        if not first and stop.wait(interval):
            return
        first = False
        eng = ctx.engine
        body = json.dumps({
            "url": self_url,
            "model": ctx.served_model,
            "mode": eng.cfg.disaggregation_mode,
            "stats": {
                "active_seqs": eng.num_active,
                "pending": len(eng.pending),
                "free_pages": eng.allocator.free_pages,
                "total_pages": eng.cfg.num_pages,
                "max_num_seqs": eng.cfg.max_num_seqs,
                **({"kvbm_host_blocks": eng.cfg.kvbm_host_blocks,
                    "kvbm_peer_port": ctx.kvbm_source.port}
                   if ctx.kvbm_source is not None else {}),
                # multi-LoRA: device-RESIDENT adapters drive the router's
                # adapter-affinity pass; host-registered ones mark this
                # worker lazy-load capable for the fallback
                **({"adapters": sorted(eng.lora.resident()),
                    "adapters_available": eng.lora.names()}
                   if eng.lora is not None else {}),
                # preemptible batch pool membership (operator manifest
                # `preemptible: true`): frontends and the planner see
                # which capacity can vanish on a reclamation notice
                **({"preemptible": True} if ctx.preemptible else {}),
                # live elasticity: the active weight version, so the
                # rollout controller and the frontend fleet view can see
                # per-pod rollout progress without scraping each worker
                "weight_version": eng.weights.version,
                # per-tenant cost rollup rides the heartbeat so every
                # frontend replica can answer /debug/costs fleet-wide
                # without fanning out scrapes to each worker
                "costs": eng.cost.rollup(),
                # step-timeline bubble summary rides the same beat: the
                # frontend's /debug/timeline merges these fleet-wide
                "timeline": eng.timeline.summary(),
                # engine health (robustness/watchdog.py): the router
                # stops picking suspect/resurrecting/quarantined workers
                # and the planner excludes quarantined capacity
                "health": eng.watchdog.summary(),
            },
        }).encode()
        for payload_url in payload_urls:
            try:
                urllib.request.urlopen(
                    urllib.request.Request(
                        payload_url, data=body,
                        headers={"Content-Type": "application/json"},
                        method="POST",
                    ),
                    timeout=5,
                )
            except Exception as e:
                # one dead replica must not starve the others of beats
                log.warning("heartbeat to %s failed: %s", payload_url, e)


def build_parser(backend_name: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog=f"dynamo_tpu.{backend_name}")
    EngineConfig.add_cli_args(p)
    p.set_defaults(**BACKEND_PROFILES.get(backend_name, {}))
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=int(os.environ.get("PORT", 8000)))
    p.add_argument("--frontend-url", default=os.environ.get("FRONTEND_URL"))
    p.add_argument("--prefill-url", default=os.environ.get("PREFILL_URL"),
                   help="comma-separated prefill worker URLs (decode role)")
    p.add_argument("--heartbeat-interval", type=float, default=3.0)
    p.add_argument("--nats-url", default=os.environ.get("NATS_URL"),
                   help="NATS server URL: serve requests over the NATS "
                        "request plane in addition to HTTP")
    p.add_argument("--kvbm-peers", default=os.environ.get("KVBM_PEERS"),
                   help="comma-separated host:port peers whose KVBM host "
                        "tiers this worker may onboard prefix blocks from "
                        "(the cross-worker KV pull; ports from peers' "
                        "/worker/stats kvbm.peer_port)")
    p.add_argument("--coordinator", default=None,
                   help="jax.distributed coordinator host:port (multi-host "
                        "gang; the Grove-multinode analogue)")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    return p


def main(argv=None, backend_name: str = "jetstream") -> None:
    logging.basicConfig(level=os.environ.get("LOG_LEVEL", "INFO"))
    p = build_parser(backend_name)
    args = p.parse_args(argv)

    if backend_name == "trtllm_tpu":
        # the compiled-engine contract: refuse to serve without an explicit
        # engine-build config, and persist compiled programs so a restart
        # "loads the engine" instead of rebuilding it
        if not getattr(args, "engine_config", None):
            p.error("--engine-config FILE is required for the trtllm_tpu "
                    "backend (the TRT engine-build config analogue)")
        # jax is already imported by this point, so the env var would be a
        # no-op — set the config knob directly (env var still wins if the
        # operator configured one)
        import jax

        if not (os.environ.get("JAX_COMPILATION_CACHE_DIR")
                or jax.config.jax_compilation_cache_dir):
            jax.config.update(
                "jax_compilation_cache_dir",
                os.path.join(os.path.expanduser("~"), ".cache", "dynamo_tpu",
                             "engine-cache"),
            )

    cfg = EngineConfig.from_cli_args(args)
    if backend_name == "trtllm_tpu" and not cfg.warmup:
        # the profile's defining contract (docs/backends.md): /ready never
        # precedes compile-completeness — not even --no-warmup or an
        # engine-config 'warmup: false' may break it
        log.warning("trtllm_tpu ignores warmup=false: the compiled-engine "
                    "profile always builds before serving")
        cfg.warmup = True
    from dynamo_tpu.parallel import distributed as dist

    dist_cfg = dist.resolve(args.coordinator, args.num_processes,
                            args.process_id)
    dist.initialize(dist_cfg)  # must precede the first backend touch
    from dynamo_tpu.utils.platform import init_backend_with_fallback

    backend = init_backend_with_fallback()
    log.info("starting %s worker: model=%s mode=%s tp=%d backend=%s "
             "process=%d/%d",
             backend_name, cfg.model, cfg.disaggregation_mode,
             cfg.tensor_parallel, backend, dist_cfg.process_id,
             dist_cfg.num_processes)
    engine = Engine(cfg)
    if cfg.warmup:
        # compile-complete before the socket opens: /ready can never observe
        # a worker that would stall first traffic on a multi-second XLA
        # compile (the reference's TRT engine-build happens pre-serve too)
        log.info("warming up: precompiling prefill buckets + decode windows")
        engine.warmup()
    if dist_cfg.enabled:
        plane = dist.ReplicationPlane(dist_cfg)
        if not dist_cfg.is_leader:
            # followers replay the leader's op stream; no HTTP surface
            dist.follower_loop(engine, plane)
            return
        engine = dist.ReplicatedEngine(engine, plane)
    ctx = ServingContext(
        engine, cfg.served_name,
        prefill_urls=(args.prefill_url.split(",") if args.prefill_url else None),
        frontend_url=args.frontend_url,
        kvbm_peers=(args.kvbm_peers.split(",") if args.kvbm_peers else None),
    )
    srv = make_server(ctx, args.host, args.port)

    if cfg.disaggregation_mode == "prefill":
        # colocated decode engines resolve this engine for the on-device
        # ici KV handoff (transfer.ici_registry); harmless cross-process
        from dynamo_tpu.transfer import ici_registry

        raw_engine = getattr(engine, "engine", engine)
        ici_registry.register(_self_url(args.host, srv.server_address[1]),
                              raw_engine)
        ici_registry.register(f"http://127.0.0.1:{srv.server_address[1]}",
                              raw_engine)

    # hardware series (tpu_tensorcore_utilization etc.) ride the same
    # /metrics endpoint — the in-process DCGM-analogue. In-process is the
    # primary path on TPU: the worker holds the chips (libtpu is
    # single-process), so only it can report real HBM/duty-cycle numbers.
    from dynamo_tpu.exporter.tpu_exporter import (
        attach_to_registry, engine_busy_sampler,
    )
    attach_to_registry(ctx.metrics.registry).set_sampler(
        engine_busy_sampler(engine)
    )

    nats_plane = None
    if args.nats_url:
        from dynamo_tpu.serving.nats_plane import WorkerNatsPlane

        try:
            nats_plane = WorkerNatsPlane(
                args.nats_url,
                f"http://127.0.0.1:{srv.server_address[1]}",
                cfg.served_name,
                advertised_url=_self_url(args.host, srv.server_address[1]),
            )
        except OSError as e:
            log.warning("NATS plane unavailable (%s); HTTP only", e)
        if nats_plane is not None and engine.prefix_cache is not None:
            # KV event plane: publish block stored/demoted/removed events
            # so the frontend's router can index this worker's real cache
            # contents (rides the request plane's NATS connection)
            from dynamo_tpu.kvbm.events import KVEventPublisher

            ctx.attach_kv_event_publisher(KVEventPublisher(
                nats_plane.nc,
                _self_url(args.host, srv.server_address[1]),
                cfg.served_name,
            ))
            log.info("kv event plane publishing on %s",
                     ctx.kv_event_publisher.subject)

    stop = threading.Event()
    hb_thread = None
    self_url = _self_url(args.host, args.port)
    if args.frontend_url:
        hb_thread = threading.Thread(
            target=heartbeat_loop,
            args=(ctx, args.frontend_url, self_url, args.heartbeat_interval, stop),
            daemon=True, name="heartbeat",
        )
        hb_thread.start()

    def shutdown(*_, deadline_s=None, wait=False):
        """Graceful drain (pod termination): stop admission (new requests
        shed 503 and the frontend fails them over), deregister from the
        frontend, give in-flight requests a grace window to finish, then
        ACTIVELY hand off journaled streams (the worker pushes its token
        journal back to the frontend, which splices a continuation on
        another replica) and demote prefix KV to the host tier for peer
        fetch. Bounded by DRAIN_TIMEOUT_S — align terminationGracePeriod
        with it. A second signal skips the drain.

        A spot reclamation notice (ServingContext.reclaim) runs this
        same, idempotent path with `deadline_s` as the HARD bound in
        place of the env budget, and `wait=True` so the notice thread
        can observe completion."""
        if stop.is_set():  # impatient second SIGTERM/SIGINT
            threading.Thread(target=srv.shutdown, daemon=True).start()
            return
        stop.set()

        def _drain():
            try:
                if deadline_s is not None:
                    # reclamation: leave margin inside the notice for the
                    # deregister round trips and the final KV demote
                    drain_s = max(1.0, deadline_s - 3.0)
                    grace_s = min(5.0, drain_s / 4.0)
                else:
                    try:
                        drain_s = float(
                            os.environ.get("DRAIN_TIMEOUT_S", "30"))
                    except ValueError:
                        log.warning("invalid DRAIN_TIMEOUT_S %r; using 30s",
                                    os.environ.get("DRAIN_TIMEOUT_S"))
                        drain_s = 30.0
                    try:
                        grace_s = float(os.environ.get(
                            "DRAIN_HANDOFF_GRACE_S", "5"))
                    except ValueError:
                        grace_s = 5.0
                # admission off FIRST: a request routed here between now
                # and the deregister sheds 503 and fails over cleanly
                ctx.begin_drain()
                if nats_plane is not None:
                    # stop consuming the NATS request plane NOW — new
                    # subjects must not refill the queue mid-drain
                    try:
                        nats_plane.close()
                    except Exception:
                        pass
                if args.frontend_url:
                    if hb_thread is not None:
                        # an IN-FLIGHT heartbeat register must land before
                        # the deregister, or it re-adds this worker
                        hb_thread.join(timeout=6.0)
                    # deregister from EVERY frontend replica the worker
                    # heartbeats to — a replica that misses the explicit
                    # deregister keeps routing here until the TTL expires
                    for fe in args.frontend_url.split(","):
                        fe = fe.strip()
                        if not fe:
                            continue
                        try:
                            urllib.request.urlopen(
                                urllib.request.Request(
                                    fe.rstrip("/") + "/internal/deregister",
                                    data=json.dumps(
                                        {"url": self_url}).encode(),
                                    headers={
                                        "Content-Type": "application/json"},
                                    method="POST",
                                ),
                                timeout=3,
                            ).close()
                        except Exception as e:
                            log.warning("deregister from %s failed (%s); "
                                        "that frontend will expire the "
                                        "heartbeat", fe, e)
                # grace: a request routed a moment before the deregister may
                # be accepted but not yet submitted — let it reach the
                # engine before the first empty check
                time.sleep(1.0)
                # drain state machine (api.ServingContext.drain): finish
                # naturally within the grace window, then hand off what
                # remains and demote prefix KV for peers
                if not ctx.drain(drain_s=drain_s,
                                 handoff_grace_s=min(grace_s, drain_s)):
                    log.warning(
                        "drain timeout with %d active / %d pending; "
                        "stopping anyway", engine.num_active,
                        len(engine.pending))
            finally:
                srv.shutdown()  # must run even if the drain itself blew up

        t = threading.Thread(target=_drain, daemon=True, name="drain")
        t.start()
        if wait:
            t.join()

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)
    # spot reclamation notices (POST /internal/reclaim, or a node
    # maintenance watcher POSTing to it) drive the same drain path under
    # the notice's hard deadline — deregister included
    ctx.reclaim_cb = lambda d: shutdown(deadline_s=d, wait=True)
    from dynamo_tpu.observability import tracing as obs_tracing

    log.info("worker listening on %s:%d (request tracing %s; spans at "
             "GET /debug/spans, kill switch DYNAMO_TPU_TRACE=0)",
             args.host, args.port,
             "on" if obs_tracing.tracing_enabled() else "off")
    if ctx.slo.targets:
        # SLO plane (docs/observability.md "SLOs and burn rates"): targets
        # come from DYNAMO_TPU_SLO_* — materialized by the operator from
        # the manifest's sloTargets key
        log.info("SLO targets active for role %s: %s (gauges on /metrics, "
                 "GET /debug/slo)", ctx.slo.role,
                 [t.label for t in ctx.slo.targets])
    try:
        srv.serve_forever()
    finally:
        if nats_plane is not None:
            nats_plane.close()
        ctx.close()  # stops the scheduler thread (and its idle_tick
        # broadcasts) BEFORE the shutdown broadcast below
        if dist_cfg.enabled and dist_cfg.is_leader:
            engine.shutdown()  # release followers from their collective


if __name__ == "__main__":
    main()
