"""Shared HTTP plumbing for the worker API server and the frontend router:
JSON responses, error envelopes, body reading, chunked SSE framing."""

from __future__ import annotations

import json
import logging
import socket
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict

from dynamo_tpu.serving import protocol as proto

log = logging.getLogger("dynamo_tpu.http")

MAX_BODY_BYTES = 10 * 1024 * 1024


class JsonHTTPHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        log.debug("%s %s", self.address_string(), fmt % args)

    def handle_one_request(self):
        # HTTP/1.1 keep-alive reuses the handler instance across requests:
        # per-request state must reset here, or request N+1 inherits
        # request N's id / SSE flag
        self._x_request_id = None
        self.sse_started = False
        super().handle_one_request()

    def request_id(self) -> str:
        """Every response carries X-Request-Id: honor the inbound header,
        else mint one (handlers that started a trace pre-seed it with the
        trace id via set_request_id, so clients correlate with spans)."""
        if not getattr(self, "_x_request_id", None):
            inbound = (self.headers.get("x-request-id")
                       if getattr(self, "headers", None) else None)
            self._x_request_id = (inbound or "").strip() or uuid.uuid4().hex
        return self._x_request_id

    def set_request_id(self, rid: str) -> None:
        if not getattr(self, "_x_request_id", None):
            self._x_request_id = rid

    def end_headers(self):
        try:
            self.send_header("X-Request-Id", self.request_id())
        except Exception:  # a response must never die on its own header
            pass
        super().end_headers()

    def _json(self, code: int, obj: Dict[str, Any]):
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _error(self, code: int, msg: str, etype: str = "invalid_request_error"):
        self._json(code, {"error": {"message": msg, "type": etype, "code": code}})

    def _raw(self, code: int, data: bytes, content_type: str):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _read_raw_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0))
        if length <= 0 or length > MAX_BODY_BYTES:
            raise proto.BadRequest("missing or oversized request body")
        return self.rfile.read(length)

    def _read_json_body(self) -> Dict[str, Any]:
        try:
            return json.loads(self._read_raw_body())
        except json.JSONDecodeError as e:
            raise proto.BadRequest(f"invalid JSON: {e}")

    # -------------------------------------------------------------- SSE ----
    sse_started: bool = False

    def _start_sse(self):
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        self.sse_started = True

    def _write_chunk(self, payload: bytes) -> bool:
        try:
            self.wfile.write(b"%x\r\n%s\r\n" % (len(payload), payload))
            self.wfile.flush()
            return True
        except (BrokenPipeError, ConnectionResetError, socket.error):
            return False

    def _sse_chunk(self, obj) -> bool:
        payload = (
            f"data: {obj}\n\n".encode()
            if isinstance(obj, str)
            else b"data: " + json.dumps(obj).encode() + b"\n\n"
        )
        return self._write_chunk(payload)

    def _end_sse(self):
        try:
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, socket.error):
            pass

    def _sse_error(self, msg: str):
        """Error delivery after SSE headers are already on the wire: an error
        event followed by [DONE], never a second HTTP status line."""
        self._sse_chunk({"error": {"message": msg, "type": "stream_error"}})
        self._sse_chunk("[DONE]")
        self._end_sse()


def make_http_server(handler_cls, attrs: Dict[str, Any], host: str, port: int):
    handler = type(f"Bound{handler_cls.__name__}", (handler_cls,), attrs)
    srv = ThreadingHTTPServer((host, port), handler)
    srv.daemon_threads = True
    return srv


def serve_forever_in_thread(srv) -> threading.Thread:
    t = threading.Thread(target=srv.serve_forever, daemon=True, name="http-server")
    t.start()
    return t
