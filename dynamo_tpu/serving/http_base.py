"""Shared HTTP plumbing for the worker API server and the frontend router:
JSON responses, error envelopes, body reading, chunked SSE framing."""

from __future__ import annotations

import json
import logging
import random
import socket
import struct
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from dynamo_tpu.robustness import faults
from dynamo_tpu.serving import protocol as proto

log = logging.getLogger("dynamo_tpu.http")

MAX_BODY_BYTES = 10 * 1024 * 1024

# every shed/routing-failure response carries a retry hint (429 admission,
# 502 failed failover, 503 no-worker/draining, 504 deadline)
RETRY_AFTER_CODES = (429, 502, 503, 504)


def retry_after_value(base_s: float = 1.0) -> str:
    """Retry-After with ±20% jitter: a burst of simultaneously-shed
    clients must not come back in lockstep and re-create the exact
    overload that shed them (docs/robustness.md)."""
    return f"{base_s * (1.0 + random.uniform(-0.2, 0.2)):.2f}"

# inference routes are the fault-injectable surface; control-plane routes
# (/internal/*, /metrics, /health) must stay reliable even mid-chaos-test
FAULTABLE_PATHS = ("/v1/", "/disagg/")


class JsonHTTPHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):
        log.debug("%s %s", self.address_string(), fmt % args)

    def handle_one_request(self):
        # HTTP/1.1 keep-alive reuses the handler instance across requests:
        # per-request state must reset here, or request N+1 inherits
        # request N's id / SSE flag
        self._x_request_id = None
        self.sse_started = False
        self._fault_reset_after_headers = False
        self._fault_closed = False
        super().handle_one_request()

    # ------------------------------------------------- fault injection ----
    def _fault_gate(self):
        """Per-request fault hook for inference routes (the robustness
        plane, docs/robustness.md). Called by worker handlers at the top of
        do_POST: a read-stall delays processing; reset-after-headers arms
        an abrupt close that end_headers() executes."""
        if not self.path.startswith(FAULTABLE_PATHS):
            return
        faults.sleep_point("worker.read_stall")
        if faults.check("worker.reset_after_headers") is not None:
            self._fault_reset_after_headers = True

    def _fault_abort_connection(self):
        """RST-close the client connection (SO_LINGER 0 so the peer sees a
        hard reset, not a clean FIN that could read as end-of-body)."""
        self._fault_closed = True
        self.close_connection = True
        try:
            self.wfile.flush()
            self.connection.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                       struct.pack("ii", 1, 0))
            self.connection.close()
        except (OSError, ValueError):
            pass

    def request_id(self) -> str:
        """Every response carries X-Request-Id: honor the inbound header,
        else mint one (handlers that started a trace pre-seed it with the
        trace id via set_request_id, so clients correlate with spans)."""
        if not getattr(self, "_x_request_id", None):
            inbound = (self.headers.get("x-request-id")
                       if getattr(self, "headers", None) else None)
            self._x_request_id = (inbound or "").strip() or uuid.uuid4().hex
        return self._x_request_id

    def set_request_id(self, rid: str) -> None:
        if not getattr(self, "_x_request_id", None):
            self._x_request_id = rid

    def end_headers(self):
        try:
            self.send_header("X-Request-Id", self.request_id())
        except Exception:  # a response must never die on its own header
            pass
        super().end_headers()
        if self._fault_reset_after_headers:
            self._fault_reset_after_headers = False
            self._fault_abort_connection()

    def _json(self, code: int, obj: Dict[str, Any],
              headers: Optional[Dict[str, str]] = None):
        data = json.dumps(obj).encode()
        try:
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            if self._fault_closed:
                return
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError, socket.error):
            # the client hung up first (e.g. its own deadline fired while
            # we were shedding): a response to nobody is just dropped
            self.close_connection = True

    def _error(self, code: int, msg: str, etype: str = "invalid_request_error",
               headers: Optional[Dict[str, str]] = None):
        headers = dict(headers or {})
        if code in RETRY_AFTER_CODES:
            # shed/overload responses carry a jittered retry hint so
            # well-behaved clients back off instead of hammering — and
            # don't all come back at once (docs/robustness.md)
            headers.setdefault("Retry-After", retry_after_value())
        self._json(code, {"error": {"message": msg, "type": etype,
                                    "code": code}}, headers=headers)

    def _raw(self, code: int, data: bytes, content_type: str):
        try:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            if self._fault_closed:
                return
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError, socket.error):
            self.close_connection = True

    def _read_raw_body(self) -> bytes:
        length = int(self.headers.get("Content-Length", 0))
        if length <= 0 or length > MAX_BODY_BYTES:
            raise proto.BadRequest("missing or oversized request body")
        return self.rfile.read(length)

    def _read_json_body(self) -> Dict[str, Any]:
        try:
            return json.loads(self._read_raw_body())
        except json.JSONDecodeError as e:
            raise proto.BadRequest(f"invalid JSON: {e}")

    # -------------------------------------------------------------- SSE ----
    sse_started: bool = False
    # class-level defaults mirror handle_one_request's per-request reset
    _fault_reset_after_headers: bool = False
    _fault_closed: bool = False

    def _start_sse(self):
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        self.sse_started = True

    def _write_chunk(self, payload: bytes) -> bool:
        if self._fault_closed:
            return False
        try:
            self.wfile.write(b"%x\r\n%s\r\n" % (len(payload), payload))
            self.wfile.flush()
            return True
        except (BrokenPipeError, ConnectionResetError, socket.error,
                ValueError):  # ValueError: write on fault-closed file
            return False

    def _sse_chunk(self, obj) -> bool:
        payload = (
            f"data: {obj}\n\n".encode()
            if isinstance(obj, str)
            else b"data: " + json.dumps(obj).encode() + b"\n\n"
        )
        return self._write_chunk(payload)

    def _end_sse(self):
        if self._fault_closed:
            return
        try:
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, socket.error,
                ValueError):
            pass

    def _sse_error(self, msg: str):
        """Error delivery after SSE headers are already on the wire: an error
        event followed by [DONE], never a second HTTP status line."""
        self._sse_chunk({"error": {"message": msg, "type": "stream_error"}})
        self._sse_chunk("[DONE]")
        self._end_sse()


def make_http_server(handler_cls, attrs: Dict[str, Any], host: str, port: int):
    handler = type(f"Bound{handler_cls.__name__}", (handler_cls,), attrs)
    srv = ThreadingHTTPServer((host, port), handler)
    srv.daemon_threads = True
    return srv


def serve_forever_in_thread(srv) -> threading.Thread:
    t = threading.Thread(target=srv.serve_forever, daemon=True, name="http-server")
    t.start()
    return t
