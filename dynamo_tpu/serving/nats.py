"""Minimal NATS client + embedded broker (core protocol, dependency-free).

The reference platform runs NATS as its frontend<->worker request plane
(/root/reference/install-dynamo-1node.sh:241-242, README.md:334). This module
makes that plane REAL here rather than ornamental: the frontend publishes
requests to per-worker / queue-group subjects and workers stream response
chunks back over reply inboxes (dynamo_tpu.serving.nats_plane).

Two pieces:
- `NatsClient`: a synchronous client speaking the standard NATS text protocol
  (INFO/CONNECT/PING/PONG/SUB/PUB/MSG, HMSG from headers-enabled servers,
  queue groups, reply inboxes) — works against the official `nats-server`
  the platform manifests deploy (deploy/platform/nats.yaml); conformance
  covered by recorded-transcript tests plus an opt-in run against the real
  binary (tests/test_nats_conformance.py).
- `MiniNatsBroker`: an in-process broker implementing the same core subset,
  used by the test suite and for single-node dev (`python -m
  dynamo_tpu.serving.nats` serves one on :4222). Subject matching supports
  the `*` token and `>` tail wildcards.

No JetStream/auth/TLS — core pub/sub is exactly what the request plane needs
(at-most-once; HTTP remains the fallback path on timeout).
"""

from __future__ import annotations

import json
import logging
import queue
import socket
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from dynamo_tpu.robustness import faults

log = logging.getLogger("dynamo_tpu.nats")

DEFAULT_PORT = 4222


def parse_url(url: str) -> Tuple[str, int]:
    """nats://host:port (scheme optional)."""
    u = url.strip()
    if "://" in u:
        u = u.split("://", 1)[1]
    if "/" in u:
        u = u.split("/", 1)[0]
    if ":" in u:
        host, port = u.rsplit(":", 1)
        return host, int(port)
    return u, DEFAULT_PORT


def subject_token(raw: str) -> str:
    """Sanitize an arbitrary string (model name, worker URL) into a single
    NATS subject token (no dots/spaces/wildcards)."""
    return "".join(c if c.isalnum() or c in "-_" else "-" for c in raw)


HEADER_VERSION_LINE = b"NATS/1.0"


def encode_headers(headers: Dict[str, str]) -> bytes:
    """Encode a NATS message-header block (the HPUB wire form): version
    line + `Key: Value` pairs + blank line. Values are sanitized of CR/LF
    so a hostile value cannot smuggle extra header lines."""
    out = [HEADER_VERSION_LINE]
    for k, v in headers.items():
        v = str(v).replace("\r", " ").replace("\n", " ")
        out.append(f"{k}: {v}".encode())
    return b"\r\n".join(out) + b"\r\n\r\n"


def decode_headers(raw: Optional[bytes]) -> Dict[str, str]:
    """Parse the raw header block off an HMSG frame into a dict (header
    names lowercased: NATS headers are case-insensitive like HTTP's).
    Tolerant: malformed lines are skipped, never raised."""
    if not raw:
        return {}
    out: Dict[str, str] = {}
    for line in raw.split(b"\r\n"):
        if not line or line.startswith(HEADER_VERSION_LINE):
            continue
        k, sep, v = line.partition(b":")
        if not sep:
            continue
        out[k.decode("utf-8", "replace").strip().lower()] = (
            v.decode("utf-8", "replace").strip())
    return out


def _subject_matches(pattern: str, subject: str) -> bool:
    pt, st = pattern.split("."), subject.split(".")
    for i, p in enumerate(pt):
        if p == ">":
            return True
        if i >= len(st):
            return False
        if p != "*" and p != st[i]:
            return False
    return len(pt) == len(st)


class _LineReader:
    """Buffered protocol reader over a socket."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.buf = b""

    def read_line(self) -> bytes:
        while b"\r\n" not in self.buf:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("nats connection closed")
            self.buf += chunk
        line, self.buf = self.buf.split(b"\r\n", 1)
        return line

    def read_exact(self, n: int) -> bytes:
        while len(self.buf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("nats connection closed")
            self.buf += chunk
        out, self.buf = self.buf[:n], self.buf[n:]
        return out


class Msg:
    __slots__ = ("subject", "reply", "data", "headers")

    def __init__(self, subject: str, reply: Optional[str], data: bytes,
                 headers: Optional[bytes] = None):
        self.subject = subject
        self.reply = reply
        self.data = data
        # raw NATS/1.0 header block from HMSG frames (None for MSG) — the
        # request plane carries trace context here (nats_plane)
        self.headers = headers

    def parsed_headers(self) -> Dict[str, str]:
        return decode_headers(self.headers)


class NatsClient:
    """Synchronous NATS client; a reader thread dispatches MSG callbacks.

    Resilient to broker restarts: on disconnect the reader thread redials
    with exponential backoff and re-issues every active subscription, so
    long-lived planes (worker responders, frontend routers) survive a
    nats-server pod bounce. Publishes during the outage raise
    ConnectionError/OSError — callers (the frontend) already treat plane
    failures as fall-back-to-HTTP."""

    RECONNECT_MAX_BACKOFF_S = 15.0

    def __init__(self, url: str, name: str = "dynamo-tpu",
                 connect_timeout: float = 5.0):
        self._url = url
        self._name = name
        self._connect_timeout = connect_timeout
        self._wlock = threading.Lock()
        self._subs: Dict[int, Callable[[Msg], None]] = {}
        # sid -> (subject, queue_group), for re-subscription after redial
        self._sub_specs: Dict[int, Tuple[str, Optional[str]]] = {}
        self._next_sid = 1
        self._closed = False
        # readiness signal (the frontend's /healthz gate): True while a
        # live connection is up, False from disconnect until redial lands
        self._connected = False
        self._connect()
        self._thread = threading.Thread(target=self._read_loop, daemon=True,
                                        name="nats-reader")
        self._thread.start()

    def _connect(self) -> None:
        host, port = parse_url(self._url)
        sock = socket.create_connection((host, port),
                                        timeout=self._connect_timeout)
        # keep the timeout through the greeting: a peer that accepts TCP but
        # never sends INFO must not hang the (sole) reconnect thread
        reader = _LineReader(sock)
        try:
            info = reader.read_line()
        except socket.timeout:
            sock.close()
            raise ConnectionError("timed out waiting for NATS INFO") from None
        if not info.startswith(b"INFO "):
            sock.close()
            raise ConnectionError(f"unexpected NATS greeting: {info[:64]!r}")
        sock.settimeout(None)
        connect = (
            b"CONNECT "
            + json.dumps({"verbose": False, "pedantic": False,
                          "name": self._name, "lang": "python",
                          "version": "0", "protocol": 1,
                          # we can PARSE HMSG (defensive), so advertising
                          # headers support is honest — a headers-enabled
                          # nats-server may then route headered publishes
                          # from other clients to us intact
                          "headers": True, "no_responders": False}).encode()
            + b"\r\n"
        )
        # re-issue active subscriptions (no-op on first connect)
        for sid, (subject, group) in list(self._sub_specs.items()):
            q = f" {group}" if group else ""
            connect += f"SUB {subject}{q} {sid}\r\n".encode()
        sock.sendall(connect)
        with self._wlock:
            self.sock = sock
            self._reader = reader
        self._connected = True

    # ------------------------------------------------------------------ io --
    def _send(self, data: bytes) -> None:
        with self._wlock:
            # _wlock exists precisely to serialize writers on this socket
            # (interleaved partial frames corrupt the protocol stream)
            self.sock.sendall(data)  # dynalint: off blocking-under-lock

    def _dispatch(self, sid: int, msg: Msg) -> None:
        cb = self._subs.get(sid)
        if cb is not None:
            try:
                cb(msg)
            except Exception:
                log.exception("nats subscription callback failed")

    def _skip_frame(self, line: bytes, parts) -> None:
        """Resync past a malformed/future-variant MSG/HMSG control line.

        Both variants advertise the payload byte count as the LAST token;
        consuming that many bytes (+CRLF) realigns the stream so one odd
        frame doesn't tear down the connection and force a full reconnect.
        When even the count is unparseable, give up on this frame and let
        the next read_line find the next control line (worst case the
        server closes on protocol error and the redial loop recovers)."""
        log.warning("malformed nats control line %r; skipping one frame",
                    line[:120])
        try:
            n = int(parts[-1])
        except (ValueError, IndexError):
            return
        if 0 <= n <= (64 << 20):  # a garbage count must not hang the reader
            self._reader.read_exact(n)
            self._reader.read_exact(2)

    def _read_loop(self) -> None:
        backoff = 0.2
        while not self._closed:
            try:
                while not self._closed:
                    line = self._reader.read_line()
                    backoff = 0.2  # healthy traffic resets the redial clock
                    # first whitespace-delimited token routes the frame:
                    # the protocol permits tab separators, which a
                    # startswith(b"MSG ") check would misroute to ignore
                    # (and then misparse the payload as control lines)
                    op = line.split(None, 1)[0] if line.strip() else b""
                    if line == b"PING":
                        self._send(b"PONG\r\n")
                    elif op == b"MSG":
                        # MSG <subject> <sid> [reply-to] <#bytes> — split()
                        # tolerates the runs of spaces/tabs the protocol
                        # permits; a malformed line costs one frame, not
                        # the whole connection (see _skip_frame)
                        # "replace" decoding: a misaligned stream can hand
                        # payload bytes to the control-line parser, and a
                        # UnicodeDecodeError here would kill the reader
                        # thread with no redial — garbage must cost frames,
                        # never the loop
                        parts = line.decode("utf-8", "replace").split()
                        if len(parts) == 5:
                            _, subject, sid, reply, nbytes = parts
                        elif len(parts) == 4:
                            _, subject, sid, nbytes = parts
                            reply = None
                        else:
                            self._skip_frame(line, parts)
                            continue
                        try:
                            n, isid = int(nbytes), int(sid)
                        except ValueError:
                            self._skip_frame(line, parts)
                            continue
                        data = self._reader.read_exact(n)
                        self._reader.read_exact(2)  # trailing CRLF
                        self._dispatch(isid, Msg(subject, reply, data))
                    elif op == b"HMSG":
                        # HMSG <subject> <sid> [reply-to] <#hdr> <#total> —
                        # sent by headers-enabled servers (nats-server 2.2+)
                        # when a peer publishes with headers. Headers ride
                        # along raw; payload is the post-header remainder.
                        parts = line.decode("utf-8", "replace").split()
                        if len(parts) == 6:
                            _, subject, sid, reply, hbytes, tbytes = parts
                        elif len(parts) == 5:
                            _, subject, sid, hbytes, tbytes = parts
                            reply = None
                        else:
                            self._skip_frame(line, parts)
                            continue
                        try:
                            nt, nh, isid = int(tbytes), int(hbytes), int(sid)
                        except ValueError:
                            self._skip_frame(line, parts)
                            continue
                        blob = self._reader.read_exact(nt)
                        self._reader.read_exact(2)  # trailing CRLF
                        self._dispatch(
                            isid,
                            Msg(subject, reply, blob[nh:], headers=blob[:nh]))
                    elif line.startswith(b"-ERR"):
                        log.warning("nats error: %s",
                                    line.decode(errors="replace"))
                    # +OK / PONG / INFO updates: ignore
            except (ConnectionError, OSError):
                if self._closed:
                    return
                self._connected = False
                log.warning("nats disconnected; redialing %s", self._url)
            try:
                # release the dead connection: a half-open socket pins the
                # broker-side port and leaks an fd per redial
                self.sock.close()
            except OSError:
                pass
            while not self._closed:
                try:
                    self._connect()
                    log.info("nats reconnected to %s (%d subscriptions)",
                             self._url, len(self._sub_specs))
                    break
                except OSError:
                    time.sleep(backoff)
                    backoff = min(backoff * 2,
                                  self.RECONNECT_MAX_BACKOFF_S)

    # ------------------------------------------------------------- surface --
    @property
    def connected(self) -> bool:
        return self._connected and not self._closed

    def publish(self, subject: str, data: bytes,
                reply: Optional[str] = None,
                headers: Optional[Dict[str, str]] = None) -> None:
        """PUB, or HPUB when `headers` is given (nats-server 2.2+ and the
        mini broker both speak it) — trace context rides NATS message
        headers exactly as it rides HTTP headers."""
        # chaos plane: a partitioned NATS fails every publish — the
        # frontend's request path falls back to HTTP, worker responders
        # fail their reply stream (docs/robustness.md)
        faults.raise_point("nats.partition", ConnectionError)
        if headers:
            hblock = encode_headers(headers)
            head = (f"HPUB {subject} {reply + ' ' if reply else ''}"
                    f"{len(hblock)} {len(hblock) + len(data)}\r\n")
            self._send(head.encode() + hblock + data + b"\r\n")
            return
        head = f"PUB {subject} {reply + ' ' if reply else ''}{len(data)}\r\n"
        self._send(head.encode() + data + b"\r\n")

    def subscribe(self, subject: str, cb: Callable[[Msg], None],
                  queue_group: Optional[str] = None) -> int:
        with self._wlock:  # sid allocation races across handler threads
            sid = self._next_sid
            self._next_sid += 1
        self._subs[sid] = cb
        self._sub_specs[sid] = (subject, queue_group)
        q = f" {queue_group}" if queue_group else ""
        self._send(f"SUB {subject}{q} {sid}\r\n".encode())
        return sid

    def unsubscribe(self, sid: int) -> None:
        self._subs.pop(sid, None)
        self._sub_specs.pop(sid, None)
        try:
            self._send(f"UNSUB {sid}\r\n".encode())
        except OSError:
            pass

    def new_inbox(self) -> str:
        return f"_INBOX.{uuid.uuid4().hex}"

    def request_stream(self, subject: str, data: bytes,
                       timeout: float = 600.0,
                       first_timeout: Optional[float] = None,
                       headers: Optional[Dict[str, str]] = None):
        """Publish with a reply inbox; yield reply Msgs until the responder
        sends a message whose JSON body has "done": true.

        `first_timeout` bounds the wait for the FIRST reply separately —
        core NATS silently drops publishes with no subscriber, so a missing
        responder should fail fast instead of eating the full stream
        timeout. Raises TimeoutError on either bound."""
        inbox = self.new_inbox()
        q: "queue.Queue[Msg]" = queue.Queue()
        sid = self.subscribe(inbox, q.put)
        try:
            self.publish(subject, data, reply=inbox, headers=headers)
            wait = first_timeout if first_timeout is not None else timeout
            while True:
                try:
                    msg = q.get(timeout=wait)
                except queue.Empty:
                    raise TimeoutError(
                        f"no reply on {subject} within {wait}s"
                    ) from None
                wait = timeout
                yield msg
                try:
                    if json.loads(msg.data).get("done"):
                        return
                except (json.JSONDecodeError, AttributeError):
                    pass
        finally:
            self.unsubscribe(sid)

    def request(self, subject: str, data: bytes,
                timeout: float = 30.0) -> bytes:
        for msg in self.request_stream(subject, data, timeout=timeout):
            return msg.data
        raise TimeoutError(f"no responder on {subject}")

    def close(self) -> None:
        self._closed = True
        try:
            self.sock.close()
        except OSError:
            pass


# ------------------------------------------------------------------ broker --


class _BrokerConn:
    def __init__(self, sock: socket.socket, broker: "MiniNatsBroker"):
        self.sock = sock
        self.broker = broker
        self.reader = _LineReader(sock)
        self.wlock = threading.Lock()
        # sid -> (subject_pattern, queue_group)
        self.subs: Dict[int, Tuple[str, Optional[str]]] = {}
        self.alive = True

    def send(self, data: bytes) -> None:
        try:
            with self.wlock:
                # wlock serializes broker->client frame writes — holding
                # it across the send IS the point (frame atomicity)
                self.sock.sendall(data)  # dynalint: off blocking-under-lock
        except OSError:
            self.alive = False

    def serve(self) -> None:
        self.send(b'INFO {"server_name":"dynamo-tpu-mini-nats","version":"0"}\r\n')
        try:
            while True:
                line = self.reader.read_line()
                verb = line.split(b" ", 1)[0].upper()
                if verb == b"CONNECT":
                    pass
                elif verb == b"PING":
                    self.send(b"PONG\r\n")
                elif verb == b"PONG":
                    pass
                elif verb == b"SUB":
                    parts = line.decode().split(" ")
                    if len(parts) == 4:
                        _, subject, group, sid = parts
                    else:
                        _, subject, sid = parts
                        group = None
                    self.subs[int(sid)] = (subject, group)
                elif verb == b"UNSUB":
                    sid = int(line.decode().split(" ")[1])
                    self.subs.pop(sid, None)
                elif verb == b"PUB":
                    parts = line.decode().split(" ")
                    if len(parts) == 4:
                        _, subject, reply, nbytes = parts
                    else:
                        _, subject, nbytes = parts
                        reply = None
                    data = self.reader.read_exact(int(nbytes))
                    self.reader.read_exact(2)
                    self.broker.route(subject, reply, data)
                elif verb == b"HPUB":
                    # HPUB <subject> [reply] <#hdr> <#total>: the first
                    # #hdr bytes of the payload are the header block
                    parts = line.decode().split(" ")
                    if len(parts) == 5:
                        _, subject, reply, hbytes, tbytes = parts
                    else:
                        _, subject, hbytes, tbytes = parts
                        reply = None
                    blob = self.reader.read_exact(int(tbytes))
                    self.reader.read_exact(2)
                    nh = int(hbytes)
                    self.broker.route(subject, reply, blob[nh:],
                                      headers=blob[:nh])
        except (ConnectionError, OSError):
            pass
        finally:
            self.alive = False
            self.broker.drop(self)


class MiniNatsBroker:
    """In-process NATS-core broker: pub/sub, queue groups, wildcards."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._srv = socket.create_server((host, port))
        self.host, self.port = self._srv.getsockname()[:2]
        self._conns: List[_BrokerConn] = []
        self._lock = threading.Lock()
        self._rr = 0  # queue-group round-robin cursor
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="mini-nats-accept"
        )
        self._closed = False
        self._accept_thread.start()

    @property
    def url(self) -> str:
        return f"nats://{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, _ = self._srv.accept()
            except OSError:
                return
            conn = _BrokerConn(sock, self)
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=conn.serve, daemon=True,
                             name="mini-nats-conn").start()

    def drop(self, conn: _BrokerConn) -> None:
        with self._lock:
            if conn in self._conns:
                self._conns.remove(conn)

    def route(self, subject: str, reply: Optional[str], data: bytes,
              headers: Optional[bytes] = None) -> None:
        """Deliver to every plain match; ONE member per queue group.
        Headered publishes fan out as HMSG (every client here advertises
        headers support in CONNECT, so no per-client downgrade path)."""
        plain: List[Tuple[_BrokerConn, int]] = []
        groups: Dict[str, List[Tuple[_BrokerConn, int]]] = {}
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            for sid, (pattern, group) in list(conn.subs.items()):
                if not _subject_matches(pattern, subject):
                    continue
                if group:
                    groups.setdefault(group, []).append((conn, sid))
                else:
                    plain.append((conn, sid))
        for group_members in groups.values():
            self._rr += 1
            plain.append(group_members[self._rr % len(group_members)])
        head_reply = f" {reply}" if reply else ""
        for conn, sid in plain:
            if headers:
                conn.send(
                    f"HMSG {subject} {sid}{head_reply} {len(headers)} "
                    f"{len(headers) + len(data)}\r\n".encode()
                    + headers + data + b"\r\n"
                )
            else:
                conn.send(
                    f"MSG {subject} {sid}{head_reply} {len(data)}\r\n".encode()
                    + data + b"\r\n"
                )

    def close(self) -> None:
        self._closed = True
        try:
            # shutdown() wakes the accept() thread; a bare close() while a
            # thread blocks in accept leaves the listener fd (and the port)
            # alive indefinitely
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass
        self._accept_thread.join(timeout=2)
        with self._lock:
            conns, self._conns = self._conns, []
        for c in conns:
            try:
                # same blocked-thread quirk as the listener: shutdown()
                # wakes the conn's recv loop so close actually releases it
                c.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.sock.close()
            except OSError:
                pass


def main() -> None:  # pragma: no cover - dev convenience
    import time

    logging.basicConfig(level="INFO")
    broker = MiniNatsBroker(host="0.0.0.0", port=DEFAULT_PORT)
    log.info("mini NATS broker on %s", broker.url)
    while True:
        time.sleep(60)


if __name__ == "__main__":  # pragma: no cover
    main()
