"""Mid-stream request recovery: the token journal + SSE relay plumbing.

PR 2 bounded failover at the CONNECT phase: once a worker started
generating, a crash mid-decode killed the client's stream, because a
naive retry would duplicate tokens. This module makes in-flight requests
recoverable state (docs/robustness.md "Recovery semantics"):

- the WORKER, when the frontend asks for journaling (``x-recovery-journal``
  header), interleaves SSE *comment* frames (``: dynr {...}``) with the
  data stream: a ``start`` record (response id + effective sampling seed)
  and, immediately BEFORE each content delta, a checkpoint carrying the
  token ids the delta covers and the cumulative content-char count;
- the FRONTEND parses the stream instead of blindly proxying bytes
  (``iter_sse_blocks``): comments feed a per-request ``RequestJournal``
  and are stripped, data frames are re-framed to the client verbatim;
- on a mid-stream failure (reset-after-headers, read stall timeout,
  crash-mid-decode's in-stream error event, EOF without ``[DONE]``) the
  frontend re-picks a healthy worker and re-POSTs the original body plus
  a ``dynamo_recovery`` extension: the journaled tokens become a
  continuation prefill (prompt ⊕ emitted tokens), sampling resumes from
  the journaled seed / PRNG-key snapshot (position-folded chains — the
  same guarantee preemption-by-recompute relies on), and the worker
  re-emits exactly the chars past ``delivered_chars`` so the seam is
  duplicate- and gap-free.

Checkpoint-before-data ordering is the exactly-once seam invariant: the
journal can only run AHEAD of delivery (``delivered_chars <= c``), never
behind, so replaying the journaled tokens always covers everything the
client saw and the skip count is exact.

Journaling is per-request opt-in by the frontend and restricted to the
shapes recovery can actually splice: streaming, single-choice (n == 1),
no tool-call gating. Everything else keeps PR 2's truncate semantics.
Kill switch: ``DYNAMO_TPU_RECOVERY=0``.

Speculative decoding composes for free: checkpoints ride TokenEvents,
which the engine emits only for ACCEPTED tokens — a journal never names
a token the target chain hasn't confirmed, and a continuation restoring
the PRNG-key snapshot resumes the identical position-folded chain even
when the crash landed mid-verify-window (docs/perf.md "Speculative
decoding v2"; tests/test_speculative.py recovery-mid-speculation).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Iterator, List, Optional, Tuple

# frontend -> worker: "journal this stream" opt-in header
JOURNAL_HEADER = "x-recovery-journal"
# body extension key carrying the continuation state on a re-dispatch
RECOVERY_BODY_KEY = "dynamo_recovery"
# SSE comment tag; SSE-compliant clients ignore comment lines, and the
# frontend relay strips them anyway
COMMENT_TAG = b": dynr "
ENV_DISABLE = "DYNAMO_TPU_RECOVERY"
# total dispatch attempts per request (initial + recoveries), matching the
# connect-phase failover bound
MAX_ATTEMPTS = 3
# prior-token cap on inbound continuations (anything longer than the
# engine's longest context is garbage by construction)
MAX_PRIOR_TOKENS = 131072


def enabled() -> bool:
    return os.environ.get(ENV_DISABLE, "1") != "0"


def journal_eligible(body: Dict) -> bool:
    """Can this request's stream be journaled and spliced? Streaming,
    single choice, no tool-call stream gating (the gate holds text back,
    so delivered chars would not be a pure function of the token ids)."""
    return (enabled()
            and isinstance(body, dict)
            and bool(body.get("stream"))
            and body.get("n", 1) == 1
            and not body.get("tools"))


def comment_frame(obj: Dict) -> bytes:
    """One journal record as an SSE comment block (worker side)."""
    return COMMENT_TAG + json.dumps(obj, separators=(",", ":")).encode() \
        + b"\n\n"


def normalize_continuation(rec) -> Dict:
    """Validate an inbound ``dynamo_recovery`` body extension (worker
    side). Raises ValueError on garbage — mapped to HTTP 400 upstream."""
    if not isinstance(rec, dict):
        raise ValueError("'dynamo_recovery' must be an object")
    toks = rec.get("prior_tokens") or []
    if (not isinstance(toks, list) or len(toks) > MAX_PRIOR_TOKENS
            or not all(isinstance(t, int) and not isinstance(t, bool)
                       and t >= 0 for t in toks)):
        raise ValueError("'prior_tokens' must be non-negative token ids")
    delivered = rec.get("delivered_chars", 0)
    if isinstance(delivered, bool) or not isinstance(delivered, int) \
            or delivered < 0:
        raise ValueError("'delivered_chars' must be a non-negative integer")
    seed = rec.get("seed")
    if seed is not None and (isinstance(seed, bool)
                             or not isinstance(seed, int)):
        raise ValueError("'seed' must be an integer")
    key = rec.get("resume_key")
    if key is not None and (
            not isinstance(key, list) or len(key) != 2
            or not all(isinstance(k, int) and not isinstance(k, bool)
                       and k >= 0 for k in key)):
        raise ValueError("'resume_key' must be two uint32 values")
    rid = rec.get("response_id")
    if rid is not None and (not isinstance(rid, str) or len(rid) > 80
                            or not rid.isprintable()):
        raise ValueError("'response_id' must be a short printable string")
    return {
        "prior_tokens": [int(t) for t in toks],
        "delivered_chars": int(delivered),
        "seed": seed,
        "resume_key": None if key is None else [int(k) for k in key],
        "response_id": rid,
        "role_sent": bool(rec.get("role_sent")),
    }


class RequestJournal:
    """Frontend-side per-request recovery state, fed by the worker's
    ``dynr`` comments and by the data frames the relay forwards."""

    def __init__(self, enabled_: bool = True):
        self.enabled = enabled_
        self.valid = True  # flips False on a journal inconsistency
        self.tokens: List[int] = []  # every token covered by a checkpoint
        self.delivered_chars = 0  # content chars actually forwarded
        self.checkpoint_chars = 0  # cumulative chars at the last checkpoint
        self.data_seen = False  # any data frame forwarded (role chunk sent)
        self.handoff = False  # the worker drained and handed the stream off
        self.response_id: Optional[str] = None
        self.seed: Optional[int] = None
        self.resume_key: Optional[List[int]] = None

    @property
    def recoverable(self) -> bool:
        return self.enabled and self.valid

    @property
    def seam_token_index(self) -> int:
        """0-based output-token index the next continuation resumes from."""
        return len(self.tokens)

    def apply_comment(self, raw: bytes) -> None:
        try:
            obj = json.loads(raw)
        except (ValueError, UnicodeDecodeError):
            self.valid = False
            return
        if not isinstance(obj, dict):
            self.valid = False
            return
        start = obj.get("start")
        if isinstance(start, dict):
            if self.response_id is None and start.get("id"):
                self.response_id = str(start["id"])
            if start.get("seed") is not None:
                self.seed = int(start["seed"])
            return
        self.tokens.extend(int(t) for t in (obj.get("t") or []))
        n = obj.get("n")
        if n is not None and int(n) != len(self.tokens):
            # a dropped/reordered checkpoint would corrupt the seam —
            # refuse to recover rather than risk duplicated tokens
            self.valid = False
        if obj.get("c") is not None:
            self.checkpoint_chars = int(obj["c"])
        if obj.get("handoff"):
            self.handoff = True
        if obj.get("key") is not None:
            try:
                self.resume_key = [int(k) for k in obj["key"]][:2]
            except (TypeError, ValueError):
                pass

    def on_data(self, payload: bytes) -> None:
        """Account a forwarded data frame's content chars."""
        self.data_seen = True
        try:
            obj = json.loads(payload)
        except (ValueError, UnicodeDecodeError):
            return
        if isinstance(obj, dict):
            self.delivered_chars += delta_content_len(obj)

    def continuation(self) -> Dict:
        """The ``dynamo_recovery`` body extension for a re-dispatch."""
        return {
            "prior_tokens": list(self.tokens),
            "delivered_chars": self.delivered_chars,
            "seed": self.seed,
            "resume_key": self.resume_key,
            "response_id": self.response_id,
            "role_sent": self.data_seen,
        }


def delta_content_len(obj: Dict) -> int:
    """Content chars carried by one streaming chunk (chat delta.content
    and legacy-completions choice.text both count; role/finish/usage
    chunks carry none)."""
    total = 0
    for ch in obj.get("choices") or []:
        if not isinstance(ch, dict):
            continue
        delta = ch.get("delta")
        if isinstance(delta, dict) and isinstance(delta.get("content"), str):
            total += len(delta["content"])
        if isinstance(ch.get("text"), str):
            total += len(ch["text"])
    return total


def iter_sse_blocks(resp) -> Iterator[Tuple[str, Optional[bytes]]]:
    """Split a worker SSE response into event blocks.

    Yields ("block", bytes) per event, then exactly one terminal marker:
    ("eof", None) on a clean end of stream, ("conn", None) when the read
    died (reset, stall timeout, chunked-coding violation). The caller
    decides whether the terminal means done (a ``[DONE]`` block arrived
    earlier) or a mid-stream failure."""
    buf = b""
    while True:
        try:
            chunk = (resp.read1(65536) if hasattr(resp, "read1")
                     else resp.read(65536))
        except Exception:
            yield ("conn", None)
            return
        if not chunk:
            yield ("eof", None)
            return
        buf += chunk
        while b"\n\n" in buf:
            block, buf = buf.split(b"\n\n", 1)
            if block.strip():
                yield ("block", block)


def parse_block(block: bytes):
    """Classify one SSE block. Returns (kind, payload):
    - ("journal", raw-json-bytes) for ``: dynr`` comments;
    - ("done", None) for the ``data: [DONE]`` sentinel;
    - ("error", None) for an in-stream error event (worker failure after
      the stream started — the recovery trigger);
    - ("data", payload-bytes) for ordinary data frames;
    - ("other", None) for anything else (forwarded verbatim)."""
    if block.startswith(COMMENT_TAG):
        return "journal", block[len(COMMENT_TAG):]
    if block.startswith(b":"):
        return "other", None
    if block.startswith(b"data:"):
        payload = block[5:].strip()
        if payload == b"[DONE]":
            return "done", None
        try:
            obj = json.loads(payload)
        except (ValueError, UnicodeDecodeError):
            return "data", payload
        if isinstance(obj, dict) and "error" in obj:
            return "error", None
        return "data", payload
    return "other", None
