"""Frontend: OpenAI-compatible HTTP entrypoint that routes to engine workers.

The TPU-native equivalent of the reference's consumed Dynamo frontend/router
pod (every DGD manifest's `Frontend` service,
/root/reference/examples/deploy/vllm/agg.yaml:12-17). Responsibilities:
- serve /v1/models (union of registered workers) and proxy
  /v1/chat/completions + /v1/completions with SSE passthrough;
- KV-affinity routing via serving.router.Router (HRW prefix hashing);
- worker membership via HTTP heartbeats (POST /internal/register) — the
  lightweight stand-in for the reference's etcd registry + NATS request plane
  (SURVEY.md §2d); an etcd-backed registry can be swapped in via
  dynamo_tpu.serving.registry;
- emit the dynamo_frontend_* metric contract at /metrics.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional

from dynamo_tpu.observability import context as obs_context
from dynamo_tpu.observability import slo as obs_slo
from dynamo_tpu.observability import tracing as obs_tracing
from dynamo_tpu.qos import tenancy as qos_tenancy
from dynamo_tpu.robustness import faults
from dynamo_tpu.robustness.breaker import STATE_CODES
from dynamo_tpu.robustness.watchdog import HEALTH_CODES as WD_HEALTH_CODES
from dynamo_tpu.robustness.deadline import Deadline
from dynamo_tpu.serving import ha
from dynamo_tpu.serving import protocol as proto
from dynamo_tpu.serving import recovery
from dynamo_tpu.serving.http_base import JsonHTTPHandler, make_http_server
from dynamo_tpu.serving.metrics import FrontendMetrics, Gauge
from dynamo_tpu.serving.router import Router, prefix_key, split_adapter
from dynamo_tpu.utils import net

log = logging.getLogger("dynamo_tpu.frontend")

# admission control: bound on concurrently proxied requests; overflow is
# answered 429 + Retry-After instead of queueing unboundedly (0 = off)
MAX_INFLIGHT_ENV = "DYNAMO_TPU_MAX_INFLIGHT"
DEFAULT_MAX_INFLIGHT = 256
# per-tenant QoS: shed over-share tenants when any matching SLO's fast
# window burns above this rate (0 disables; only meaningful with tenants
# configured AND SLO targets declared — docs/robustness.md)
BURN_SHED_ENV = "DYNAMO_TPU_QOS_BURN_SHED"
DEFAULT_BURN_SHED = 2.0
# preemptible batch tier: the PR 7 burn gate INVERTED — batch-class
# tenants admit only while every interactive SLO fast window burns BELOW
# this rate (interactive load is quiet); at/above it new batch work is
# paused with 429 batch_paused (0 disables the gate — batch admits like
# any tenant; docs/robustness.md "Preemptible batch tier")
BATCH_BURN_ADMIT_ENV = "DYNAMO_TPU_BATCH_BURN_ADMIT"
DEFAULT_BATCH_BURN_ADMIT = 1.0


def _env_max_inflight() -> int:
    try:
        return max(0, int(os.environ.get(MAX_INFLIGHT_ENV,
                                         DEFAULT_MAX_INFLIGHT)))
    except ValueError:
        return DEFAULT_MAX_INFLIGHT


def _env_burn_shed() -> float:
    try:
        return max(0.0, float(os.environ.get(BURN_SHED_ENV,
                                             DEFAULT_BURN_SHED)))
    except ValueError:
        return DEFAULT_BURN_SHED


def _env_batch_burn_admit() -> float:
    try:
        return max(0.0, float(os.environ.get(BATCH_BURN_ADMIT_ENV,
                                             DEFAULT_BATCH_BURN_ADMIT)))
    except ValueError:
        return DEFAULT_BATCH_BURN_ADMIT

# re-export: requests slower than this log a WARNING carrying their trace
# id — the exemplar-style bridge from the dynamo_frontend_* latency series
# to /debug/spans?trace_id=... (see docs/observability.md)
slow_request_threshold_s = obs_tracing.slow_request_threshold_s


class FrontendContext:
    def __init__(self, router: Optional[Router] = None,
                 nats_url: Optional[str] = None,
                 max_inflight: Optional[int] = None,
                 gossip_interval_s: Optional[float] = None):
        self.router = router or Router()
        self.metrics = FrontendMetrics()
        self.worker_gauge = Gauge(
            "dynamo_frontend_workers", "Registered live workers",
            self.metrics.registry,
        )
        # live elasticity: fleet rollout progress at a glance — how many
        # live workers heartbeat each weight version (label death keeps
        # finished rollouts from leaving a zero-worker version row)
        self.worker_version_gauge = Gauge(
            "dynamo_frontend_worker_weight_version",
            "Live workers by heartbeat-reported weight version",
            self.metrics.registry, labelnames=("version",),
        )
        self._version_labels: set = set()
        from dynamo_tpu.serving.metrics import Counter

        self.ledger_counter = Counter(
            "dynamo_frontend_kv_overlap_routed_total",
            "Requests routed by the KV-overlap prefix ledger",
            self.metrics.registry,
        )
        self.router.ledger_counter = self.ledger_counter
        # --- KV event plane (dynamo_tpu.kvbm.events) ---
        self.kv_index_counter = Counter(
            "dynamo_frontend_kv_event_index_routed_total",
            "Requests routed by the worker-published KV event index",
            self.metrics.registry,
        )
        self.router.kv_index_counter = self.kv_index_counter
        self.kv_events_counter = Counter(
            "dynamo_frontend_kv_events_total",
            "Worker KV cache events received on the event plane",
            self.metrics.registry,
        )
        self.kv_index_gauge = Gauge(
            "dynamo_frontend_kv_event_index_blocks",
            "Blocks tracked by the KV event index", self.metrics.registry,
        )
        # --- robustness plane (docs/robustness.md) ---
        self.max_inflight = (max_inflight if max_inflight is not None
                             else _env_max_inflight())
        # --- per-tenant QoS (dynamo_tpu.qos; docs/robustness.md
        # "Per-tenant QoS") --- tenant classes from DYNAMO_TPU_TENANTS;
        # admission becomes per-tenant: weighted in-flight caps, SLO-burn
        # shedding of over-share tenants, and a Retry-After derived from
        # the shed tenant's own budget-refill time. With no tenants
        # configured everything resolves to "default" and only the global
        # bound applies — byte-identical to the pre-QoS frontend.
        self.tenants = qos_tenancy.TenantRegistry.from_env()
        self.tenant_admission = qos_tenancy.TenantAdmission(
            self.tenants, self.max_inflight)
        self.burn_shed_threshold = _env_burn_shed()
        self.batch_burn_admit = _env_batch_burn_admit()
        self._burn_cache: Optional[tuple] = None  # (monotonic ts, rows)
        self.admission_rejected = Counter(
            "dynamo_frontend_admission_rejected_total",
            "Requests shed with 429 by admission control, by tenant and "
            "reason (inflight = per-tenant weighted cap; budget = global "
            "in-flight bound; slo_burn = SLO fast-burn shed of an "
            "over-share tenant; batch_paused = batch-class tenant held "
            "back while interactive SLO burn is hot)",
            self.metrics.registry, labelnames=("tenant", "reason"),
        )
        self.tenant_inflight_gauge = Gauge(
            "dynamo_tenant_inflight",
            "In-flight proxied requests by tenant",
            self.metrics.registry, labelnames=("tenant",),
        )
        self.deadline_shed = Counter(
            "dynamo_frontend_deadline_shed_total",
            "Requests shed with 504 because their deadline budget was "
            "exhausted before a worker answered",
            self.metrics.registry,
        )
        self.expired_counter = Counter(
            "dynamo_frontend_worker_expired_total",
            "Workers purged because their registration refresh lapsed, by "
            "the registration path that went quiet (direct = the worker's "
            "own heartbeat; peer = another frontend's NATS worker-gossip "
            "relay; etcd = a registry merge record)",
            self.metrics.registry, labelnames=("reason",),
        )
        self.router.expired_counter = self.expired_counter
        self.breaker_open_counter = Counter(
            "dynamo_frontend_breaker_open_total",
            "Circuit-breaker open transitions (threshold trips and failed "
            "half-open probes)",
            self.metrics.registry, labelnames=("worker",),
        )
        self.breaker_gauge = Gauge(
            "dynamo_frontend_breaker_state",
            "Per-worker circuit-breaker state (0=closed 1=half_open 2=open)",
            self.metrics.registry, labelnames=("worker",),
        )
        self.worker_health_gauge = Gauge(
            "dynamo_frontend_worker_health",
            "Per-worker engine health from heartbeats (0=healthy "
            "1=suspect 2=resurrecting 3=quarantined) — the fleet view "
            "the planner excludes quarantined capacity with",
            self.metrics.registry, labelnames=("worker",),
        )
        # --- request recovery plane (serving/recovery.py) ---
        self.recovered_counter = Counter(
            "dynamo_frontend_recovered_total",
            "Requests recovered after a worker failure, by phase (connect "
            "= pre-send failover re-pick; stream = mid-stream journaled "
            "continuation spliced onto the same client stream)",
            self.metrics.registry, labelnames=("phase",),
        )
        self.router.breakers.on_open = (
            lambda url: self.breaker_open_counter.inc(worker=url))
        self.tracer = obs_tracing.Tracer("frontend")
        # --- SLO plane (observability/slo.py): multi-window burn rate from
        # the latency histograms above; targets from DYNAMO_TPU_SLO_* (the
        # operator materializes the manifest's sloTargets key into them)
        self.slo = obs_slo.SLOEngine(self.metrics, role="frontend")
        from dynamo_tpu.serving.metrics import CallbackCounter

        CallbackCounter(
            "dynamo_spans_dropped_total",
            "Finished spans evicted from the ring buffer before any "
            "scrape could lift them (size: DYNAMO_TPU_TRACE_BUFFER)",
            self.metrics.registry,
            lambda: self.tracer.collector.dropped_total,
        )
        # in-flight request tracking feeds the queued-requests gauge the
        # operator's planner scrapes for autoscaling
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self.start_time = time.time()
        # NATS request plane (the reference's frontend<->worker transport,
        # /root/reference/install-dynamo-1node.sh:241-242); HTTP remains the
        # fallback when the plane is down or unset
        self.nats = None
        # --- HA frontend plane (serving/ha.py; docs/robustness.md "HA
        # frontend plane") — replicated journal, resume claims, gossiped
        # tenant counters, worker-membership relay. All of it rides the
        # NATS plane; without a nats_url this frontend is standalone and
        # behaves byte-identically to the pre-HA stack.
        self.frontend_id = ha.frontend_id()
        self.journal_plane: Optional[ha.JournalPlane] = None
        self.tenant_gossip: Optional[ha.TenantGossip] = None
        self.worker_gossip: Optional[ha.WorkerGossip] = None
        self.draining = False  # flipped by SIGTERM; /healthz goes 503
        self.ha_journal_records = Counter(
            "dynamo_frontend_ha_journal_records_total",
            "Recovery-journal records re-published to / applied from the "
            "NATS journal plane, by direction",
            self.metrics.registry, labelnames=("direction",),
        )
        self.ha_journal_streams = Gauge(
            "dynamo_frontend_ha_journal_streams",
            "Streams tracked in the replicated journal store",
            self.metrics.registry,
        )
        self.ha_resumes = Counter(
            "dynamo_frontend_ha_resumes_total",
            "Cross-frontend stream resume attempts by outcome (resumed | "
            "unknown = no journal record for the response id | stale_cursor "
            "= record behind the client's delivered chars | invalid = "
            "n-gap/missing start record | completed = stream already done | "
            "lost_claim = another frontend won the resume | no_worker)",
            self.metrics.registry, labelnames=("outcome",),
        )
        self.ha_gossip = Counter(
            "dynamo_frontend_ha_gossip_messages_total",
            "Tenant-counter gossip snapshots by direction",
            self.metrics.registry, labelnames=("direction",),
        )
        self.ha_peer_frontends = Gauge(
            "dynamo_frontend_ha_peer_frontends",
            "Peer frontends with a fresh tenant-gossip snapshot",
            self.metrics.registry,
        )
        self.ha_peer_inflight = Gauge(
            "dynamo_frontend_ha_peer_inflight",
            "Gossiped peer-replica in-flight requests by tenant",
            self.metrics.registry, labelnames=("tenant",),
        )
        if nats_url:
            from dynamo_tpu.serving.nats import NatsClient

            self.nats = NatsClient(nats_url, name="frontend")
            # KV event plane: workers publish block stored/demoted/removed
            # events; the router's KVEventIndex turns them into the
            # primary kv_overlap routing source (ledger = fallback)
            self.nats.subscribe("dynamo.kv_events.>", self._on_kv_event)
            self.journal_plane = ha.JournalPlane(self.nats, self.frontend_id)
            self.journal_plane.published_counter = self.ha_journal_records
            self.journal_plane.applied_counter = self.ha_journal_records
            self.tenant_gossip = ha.TenantGossip(
                self.nats, self.frontend_id, self.tenant_admission,
                interval_s=gossip_interval_s)
            self.tenant_gossip.gossip_counter = self.ha_gossip
            # fold gossiped peer counts into admission: caps/over-share
            # become fleet-wide within the gossip staleness bound
            self.tenant_admission.peer_counts_fn = (
                self.tenant_gossip.peer_counts)
            self.worker_gossip = ha.WorkerGossip(self.nats,
                                                 self.frontend_id,
                                                 self.router)

    def _on_kv_event(self, msg) -> None:
        try:
            payload = json.loads(msg.data)
        except (json.JSONDecodeError, UnicodeDecodeError):
            return
        if self.router.kv_index.apply(payload):
            self.kv_events_counter.inc()

    # ----------------------------------------- per-tenant admission ----
    def admit(self, tenant: str):
        """Admission decision for one request. Returns
        ``(admitted, reason, retry_after_s)``; an admitted request MUST be
        paired with release(). Checks, in order: the tenant's weighted
        in-flight cap, the SLO fast-burn shed (over-share tenants only —
        shedding is by tenant, never global), then the global bound."""
        adm = self.tenant_admission
        if self.tenants.enabled:
            if not adm.try_admit(tenant):
                return False, "inflight", adm.retry_after_s(tenant)
        else:
            adm.admit_unchecked(tenant)
        # the tenant slot is reserved: every shed below must release it
        if self._batch_paused(tenant):
            adm.release(tenant)
            return False, "batch_paused", adm.retry_after_s(tenant)
        if self._slo_burn_shed(tenant):
            adm.release(tenant)
            return False, "slo_burn", adm.retry_after_s(tenant)
        with self._inflight_lock:
            if self.max_inflight and self._inflight >= self.max_inflight:
                over = True
            else:
                self._inflight += 1
                over = False
        if over:
            adm.release(tenant)
            return False, "budget", adm.retry_after_s(tenant)
        return True, "", 0.0

    def release(self, tenant: str, duration_s: Optional[float] = None):
        with self._inflight_lock:
            self._inflight -= 1
        self.tenant_admission.release(tenant, duration_s)

    def _batch_paused(self, tenant: str) -> bool:
        """Inverted burn gate for the preemptible batch tier: a
        batch-class tenant admits only while the fast SLO window is
        QUIET (burn < batch_burn_admit on every interactive row). The
        normal shed asks "is the burn hot enough to shed over-share
        tenants?"; this asks "is it quiet enough to let offline work
        in at all?" — batch never waits on over_share, its mere
        presence during a burn is the problem. No SLO configured means
        no signal: batch admits (the engine-side class eviction still
        protects interactive latency)."""
        thr = self.batch_burn_admit
        if (thr <= 0 or not self.tenants.enabled
                or not self.tenants.is_batch(tenant)):
            return False
        fast = min(self.slo.windows_s) if self.slo.windows_s else 0
        for row in self._burn_rows():
            if row.get("window_s") != fast:
                continue
            if self.tenants.is_batch(row.get("tenant", "*")):
                continue  # the batch tier's own burn never pauses itself
            if row.get("burn_rate", 0.0) >= thr:
                return True
        return False

    def _slo_burn_shed(self, tenant: str) -> bool:
        """SLO-aware admission: when any matching SLO objective's FAST
        window burns above the threshold, shed tenants holding more than
        their weighted share of the in-flight load (the likely pressure
        source); under-share tenants keep admitting — the burn must never
        become a global gate."""
        thr = self.burn_shed_threshold
        if (thr <= 0 or not self.tenants.enabled
                or not self.tenant_admission.over_share(tenant)):
            return False
        fast = min(self.slo.windows_s) if self.slo.windows_s else 0
        for row in self._burn_rows():
            if row.get("window_s") != fast:
                continue
            row_tenant = row.get("tenant", "*")
            if row_tenant not in ("*", tenant):
                continue
            if row.get("burn_rate", 0.0) > thr:
                return True
        return False

    def _burn_rows(self):
        """SLO evaluations, cached ~1s — admission must not re-walk the
        whole burn-bucket machinery on every request of a burst."""
        now = time.monotonic()
        if self._burn_cache is not None and now - self._burn_cache[0] < 1.0:
            return self._burn_cache[1]
        try:
            rows = self.slo.evaluate()
        except Exception:
            log.exception("slo evaluation failed; burn shed skipped")
            rows = []
        self._burn_cache = (now, rows)
        return rows

    # ------------------------------------------------------- readiness ----
    def readiness(self) -> tuple:
        """(ready, detail) for /healthz — a REAL gate, not a liveness ping:
        unready while draining, while the NATS journal/KV-event/gossip
        subscriptions are down (this replica would journal nothing and see
        stale counters), or while the worker registry is empty (nothing to
        route to). The VIP's readinessProbe stops sending traffic here."""
        workers = len(self.router.alive(("agg", "prefill", "decode")))
        nats_ok = self.nats is None or self.nats.connected
        detail = {
            "workers": workers,
            "nats": ("unconfigured" if self.nats is None
                     else ("connected" if nats_ok else "disconnected")),
            "draining": self.draining,
            "frontend_id": self.frontend_id,
        }
        ready = workers > 0 and nats_ok and not self.draining
        return ready, detail


class _FrontendHandler(JsonHTTPHandler):
    ctx: FrontendContext
    _tenant = qos_tenancy.DEFAULT_TENANT  # set per-request in _proxy

    # ---------------------------------------------------------------- routes
    def do_GET(self):
        path = self.path.split("?")[0]
        ctx = self.ctx
        if path == "/v1/models":
            # base models plus every '<base>:<adapter>' any live worker
            # can serve (multi-LoRA addressing)
            self._json(200, proto.models_response(
                ctx.router.models_with_adapters()))
        elif path.startswith("/v1/models/"):
            mid = path[len("/v1/models/"):]
            if mid in ctx.router.models_with_adapters():
                self._json(200, proto.model_response(mid))
            else:
                self._error(404, f"model {mid!r} not found", "not_found")
        elif path == "/metrics":
            ctx.worker_gauge.set(len(ctx.router.alive(("agg", "prefill", "decode"))))
            ctx.kv_index_gauge.set(ctx.router.kv_index.stats()["entries"])
            with ctx._inflight_lock:
                ctx.metrics.queued.set(ctx._inflight)
            # breaker state is scrape-time truth (open->half_open happens
            # by clock, not by an event anyone could have observed)
            for url, state in ctx.router.breakers.snapshot().items():
                ctx.breaker_gauge.set(STATE_CODES[state], worker=url)
            # engine health rides worker heartbeats; scrape-time export
            # with label death so a departed worker's row disappears
            health_now = {w.url: WD_HEALTH_CODES.get(w.health, 0)
                          for w in ctx.router.alive(
                              ("agg", "prefill", "decode"))}
            with ctx.worker_health_gauge._lock:
                known_workers = [dict(lbl).get("worker")
                                 for lbl in ctx.worker_health_gauge._values]
            for u in known_workers:
                if u not in health_now:
                    ctx.worker_health_gauge.remove(worker=u)
            for u, code in health_now.items():
                ctx.worker_health_gauge.set(code, worker=u)
            # per-tenant in-flight occupancy (tenants that drained to zero
            # must read 0, not freeze at their last value)
            inflight = ctx.tenant_admission.snapshot()["inflight"]
            with ctx.tenant_inflight_gauge._lock:
                known = [dict(lbl).get("tenant")
                         for lbl in ctx.tenant_inflight_gauge._values]
            for t in known:
                if t not in inflight:
                    ctx.tenant_inflight_gauge.set(0, tenant=t)
            for t, n in inflight.items():
                ctx.tenant_inflight_gauge.set(n, tenant=t)
            # HA plane gauges are scrape-time truth (store size and peer
            # freshness both move without any local event)
            if ctx.journal_plane is not None:
                ctx.ha_journal_streams.set(len(ctx.journal_plane))
            if ctx.tenant_gossip is not None:
                ctx.ha_peer_frontends.set(ctx.tenant_gossip.live_peers())
                peer = ctx.tenant_gossip.peer_counts()
                with ctx.ha_peer_inflight._lock:
                    known = [dict(lbl).get("tenant")
                             for lbl in ctx.ha_peer_inflight._values]
                for t in known:
                    if t not in peer:
                        ctx.ha_peer_inflight.set(0, tenant=t)
                for t, n in peer.items():
                    ctx.ha_peer_inflight.set(n, tenant=t)
            by_ver: dict = {}
            for w in ctx.router.alive(("agg", "prefill", "decode")):
                v = (w.stats or {}).get("weight_version")
                if v:
                    by_ver[v] = by_ver.get(v, 0) + 1
            for v in ctx._version_labels - set(by_ver):
                ctx.worker_version_gauge.remove(version=v)
            for v, n in by_ver.items():
                ctx.worker_version_gauge.set(n, version=v)
            ctx._version_labels = set(by_ver)
            ctx.slo.refresh_gauges()
            body, ctype = ctx.metrics.registry.scrape(
                self.headers.get("Accept"))
            self._raw(200, body, ctype)
        elif path == "/internal/faults":
            self._json(200, faults.http_payload())
        elif path in ("/health", "/live", "/ready"):
            workers = len(ctx.router.alive(("agg", "prefill", "decode")))
            code = 200 if path != "/ready" or workers > 0 else 503
            self._json(code, {"status": "ok" if code == 200 else "no-workers",
                              "workers": workers})
        elif path == "/healthz":
            # the readiness gate the VIP probes (operator readinessProbe):
            # unlike /health it goes 503 whenever this replica could not
            # actually serve — NATS subscriptions down, no workers, or
            # draining (docs/robustness.md "HA frontend plane")
            ready, detail = ctx.readiness()
            detail["status"] = "ready" if ready else "unready"
            self._json(200 if ready else 503, detail)
        elif path == "/internal/workers":
            alive = ctx.router.alive(("agg", "prefill", "decode"))
            versions: dict = {}
            for w in alive:
                v = (w.stats or {}).get("weight_version")
                if v:
                    versions[v] = versions.get(v, 0) + 1
            self._json(200, {
                "workers": [
                    {"url": w.url, "model": w.model, "mode": w.mode,
                     "headroom": round(w.headroom, 3), "stats": w.stats}
                    for w in alive
                ],
                # per-version worker counts: the rollout controller's
                # cheap fleet-progress read (mirrors the
                # dynamo_frontend_worker_weight_version gauge)
                "weight_versions": versions,
            })
        elif path == "/debug/spans":
            from urllib.parse import parse_qs, urlparse

            qs = parse_qs(urlparse(self.path).query)
            self._json(200, obs_tracing.spans_debug_payload(
                qs, ctx.tracer.collector))
        elif path == "/debug/slo":
            from urllib.parse import parse_qs, urlparse

            qs = parse_qs(urlparse(self.path).query)
            self._json(200, obs_slo.debug_slo_payload(ctx.slo, qs))
        elif path == "/debug/tenants":
            # per-tenant QoS introspection: classes, caps, live in-flight
            self._json(200, {
                "enabled": ctx.tenants.enabled,
                "classes": ctx.tenants.describe(),
                "admission": ctx.tenant_admission.snapshot(),
                "burn_shed_threshold": ctx.burn_shed_threshold,
            })
        elif path == "/debug/costs":
            # fleet-wide chargeback rollup: every worker ships its cost
            # ledger in the heartbeat, so this aggregates registry state —
            # no scrape fan-out, and it works identically on every HA
            # frontend replica (heartbeats go to all of them)
            from dynamo_tpu.observability.cost import merge_rollups

            per_worker = {}
            for w in ctx.router.alive(("agg", "prefill", "decode")):
                costs = (w.stats or {}).get("costs")
                if costs:
                    per_worker[w.url] = costs
            merged = merge_rollups(list(per_worker.values()))
            merged["workers"] = len(per_worker)
            merged["per_worker"] = per_worker
            self._json(200, merged)
        elif path == "/debug/timeline":
            # fleet-wide bubble attribution: each worker ships its
            # step-timeline summary in the heartbeat (same no-fan-out
            # pattern as /debug/costs); quantiles don't merge, so the
            # rollup reports worst-worker p95 per phase
            from dynamo_tpu.observability.timeline import merge_summaries

            per_worker = {}
            for w in ctx.router.alive(("agg", "prefill", "decode")):
                tl = (w.stats or {}).get("timeline")
                if tl:
                    per_worker[w.url] = tl
            merged = merge_summaries(list(per_worker.values()))
            merged["workers"] = len(per_worker)
            merged["per_worker"] = per_worker
            self._json(200, merged)
        elif path in ("/debug", "/debug/"):
            self._json(200, {"endpoints": {
                "/debug/spans": "recent frontend/request spans "
                                "(?trace_id=&n=)",
                "/debug/slo": "SLO attainment windows and violation "
                              "breakdown",
                "/debug/tenants": "tenant classes, caps, live admission "
                                  "state",
                "/debug/costs": "fleet-wide per-tenant cost rollup "
                                "aggregated from worker heartbeats",
                "/debug/timeline": "fleet-wide step-timeline bubble "
                                   "attribution aggregated from worker "
                                   "heartbeats",
            }, "see_also": {
                "workers": "GET <worker>/debug/ for the worker-side index "
                           "(flight recorder, trace capture, costs)",
                "planner": "GET /debug/planner lives on the operator "
                           "debug server, not this frontend",
            }})
        else:
            self._error(404, f"no route {path}")

    def do_POST(self):
        path = self.path.split("?")[0]
        try:
            if path == "/internal/register":
                body = self._read_json_body()
                self.ctx.router.register(
                    body["url"], body.get("model", "?"),
                    body.get("mode", "agg"), body.get("stats"),
                )
                if self.ctx.worker_gossip is not None:
                    # relay the DIRECT heartbeat to peer frontends so a
                    # worker heartbeating here is never TTL-purged by a
                    # replica that can't hear it (serving/ha.py)
                    self.ctx.worker_gossip.publish_register(
                        body["url"], body.get("model", "?"),
                        body.get("mode", "agg"), body.get("stats"))
                self._json(200, {"ok": True})
            elif path == "/internal/deregister":
                # graceful worker drain (SIGTERM): stop routing to it NOW
                # instead of waiting out the heartbeat TTL
                body = self._read_json_body()
                self.ctx.router.deregister(body["url"])
                if self.ctx.worker_gossip is not None:
                    # a drain is authoritative fleet-wide
                    self.ctx.worker_gossip.publish_deregister(body["url"])
                self._json(200, {"ok": True})
            elif path == "/internal/faults":
                try:
                    self._json(200, faults.http_configure(
                        self._read_json_body()))
                except ValueError as e:
                    self._error(400, str(e))
            elif path in ("/v1/chat/completions", "/v1/completions"):
                self._proxy(path)
            else:
                self._error(404, f"no route {path}")
        except proto.BadRequest as e:
            self._error(400, str(e))
        except Exception:
            log.exception("frontend request failed")
            self._error(500, "internal error", "internal_error")

    def _send_nats_response(self, parts, model: str, t0: float,
                            exemplar=None):
        """Write a NATS-plane response out. The response has STARTED once we
        are here — mid-stream failures truncate (never re-dispatch to the
        HTTP plane, which would re-run inference and corrupt the stream)."""
        ctx = self.ctx
        m = ctx.metrics
        status, ctype, chunks = parts
        if "text/event-stream" in ctype:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            first = True
            try:
                for chunk in chunks:
                    if first:
                        m.ttft.observe(time.monotonic() - t0,
                                       exemplar=exemplar, model=model)
                        m.tenant_ttft.observe(time.monotonic() - t0,
                                              tenant=self._tenant)
                        first = False
                    self.wfile.write(b"%x\r\n%s\r\n" % (len(chunk), chunk))
                    self.wfile.flush()
                self.wfile.write(b"0\r\n\r\n")
            except (BrokenPipeError, ConnectionResetError, socket.error):
                pass
            except Exception:
                log.exception("NATS stream truncated mid-response")
        else:
            payload = b"".join(chunks)
            m.ttft.observe(time.monotonic() - t0, exemplar=exemplar,
                           model=model)
            m.tenant_ttft.observe(time.monotonic() - t0,
                                  tenant=self._tenant)
            try:
                usage = json.loads(payload).get("usage", {})
                m.isl.observe(usage.get("prompt_tokens", 0), model=model)
                m.osl.observe(usage.get("completion_tokens", 0), model=model)
            except Exception:
                pass
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)
        m.duration.observe(time.monotonic() - t0, exemplar=exemplar,
                           model=model)

    # ----------------------------------------------------------------- proxy
    def _proxy(self, path: str):
        # in-flight accounting spans the WHOLE proxied exchange (SSE
        # passthrough included) — it is the queued-requests signal the
        # operator's planner autoscales on. Admission is per-tenant
        # (docs/robustness.md "Per-tenant QoS"): the tenant identity is
        # resolved from the client's headers at this edge, weighted
        # in-flight caps and the SLO-burn shed apply per tenant, and a
        # shed response carries a Retry-After derived from THAT tenant's
        # budget-refill time rather than the global jitter.
        ctx = self.ctx
        tenant = ctx.tenants.resolve(self.headers)
        self._tenant = tenant
        ctx.metrics.tenant_requests.inc(tenant=tenant)
        admitted, reason, retry_after = ctx.admit(tenant)
        if not admitted:
            ctx.admission_rejected.inc(tenant=tenant, reason=reason)
            detail = {
                "inflight": f"tenant {tenant!r} is at its in-flight cap "
                            f"({ctx.tenant_admission.cap(tenant)})",
                "budget": f"too many in-flight requests "
                          f"(limit {ctx.max_inflight})",
                "slo_burn": f"SLO budget is burning and tenant {tenant!r} "
                            "is over its fair share",
                "batch_paused": f"batch tenant {tenant!r} is paused while "
                                "interactive SLO burn is hot",
            }[reason]
            self._error(
                429, f"{detail}; retry shortly", "rate_limit_exceeded",
                headers={"Retry-After": f"{retry_after:.2f}"})
            return
        t_admit = time.monotonic()
        try:
            self._proxy_inner(path)
        finally:
            ctx.release(tenant, time.monotonic() - t_admit)

    def _proxy_inner(self, path: str):
        ctx = self.ctx
        raw = self._read_raw_body()
        try:
            body = json.loads(raw)
        except json.JSONDecodeError as e:
            raise proto.BadRequest(f"invalid JSON: {e}")
        if path.endswith("chat/completions"):
            parsed = proto.parse_chat_request(body)
            prompt_text = json.dumps(parsed["messages"])
        else:
            parsed = proto.parse_completion_request(body)
            prompt_text = parsed["prompt"]
        affinity = prefix_key(prompt_text)
        model = parsed["model"]

        # --- distributed tracing: this span is the trace ROOT unless the
        # client sent its own traceparent; x-request-id (inbound or minted
        # from the trace id) rides every response for correlation ---
        inbound_rid = ((self.headers.get("x-request-id") or "").strip()
                       or None)
        # end-to-end deadline: the client's x-deadline budget (clamped to
        # the operator default) starts counting down NOW; every downstream
        # hop gets the remainder
        deadline = Deadline.from_headers(self.headers)
        parent = obs_context.extract_context(self.headers)
        span = ctx.tracer.start_span(
            "frontend.request", parent=parent, kind="server",
            trace_seed=inbound_rid,
            attributes={"http.path": path, "model": model,
                        "deadline_s": round(deadline.budget_s, 3),
                        "stream": bool(parsed.get("stream")),
                        "tenant.id": self._tenant})
        rid = inbound_rid or (span.trace_id if span.recording else None)
        if rid:
            self.set_request_id(rid)
        # downstream hops get the SPAN as parent (or pass the inbound
        # context through untouched when tracing is switched off)
        trace_headers: dict = {}
        obs_context.inject_context(
            span.context if span.recording else parent, trace_headers,
            request_id=rid)
        # the resolved tenant identity rides EVERY downstream dispatch —
        # worker POSTs, the NATS plane, and recovery-continuation
        # re-dispatches all build their headers from trace_headers, so
        # the edge's decision survives failover and mid-stream recovery
        trace_headers[qos_tenancy.RESOLVED_HEADER] = self._tenant
        t_req = time.monotonic()
        try:
            if body.get(ha.RESUME_BODY_KEY) is not None:
                # a client resuming a stream whose original frontend died
                # (serving/ha.py): any replica can pick it up from the
                # replicated journal
                self._resume_stream(path, body, prompt_text, affinity,
                                    model, span, trace_headers, deadline)
            else:
                self._route_and_forward(path, raw, body, prompt_text,
                                        affinity, model, span,
                                        trace_headers, deadline)
        except Exception as e:
            span.set_status("ERROR", f"{type(e).__name__}: {e}")
            raise
        finally:
            dur = time.monotonic() - t_req
            span.set_attribute("duration_s", round(dur, 6))
            span.end()
            if span.recording and dur >= slow_request_threshold_s():
                log.warning(
                    "slow request: %.2fs model=%s path=%s trace_id=%s "
                    "x_request_id=%s — GET /debug/spans?trace_id=%s",
                    dur, model, path, span.trace_id, rid or "-",
                    span.trace_id)

    def _shed_deadline(self, span, where: str, model: Optional[str] = None):
        self.ctx.deadline_shed.inc()
        if model:
            self.ctx.metrics.errors_total.inc(model=model, code="504")
        span.set_status("ERROR", f"deadline exhausted ({where})")
        self._error(
            504, f"deadline budget exhausted {where}; request shed",
            "timeout")

    def _route_and_forward(self, path: str, raw: bytes, body: dict,
                           prompt_text: str, affinity: str, model: str,
                           span, trace_headers: dict, deadline: Deadline):
        ctx = self.ctx
        # exemplar: latency observations carry the trace id, so a hot
        # histogram bucket links straight to /debug/spans?trace_id=...
        ex = span.trace_id if span.recording else None
        if deadline.expired:
            # shed BEFORE routing: no pick, no dial, no engine slot
            self._shed_deadline(span, "before routing", model)
            return
        # multi-LoRA addressing: '<base>:<adapter>' routes on the BASE
        # model's worker set with adapter-affinity (resident > lazy-load
        # capable > any); the worker re-validates the adapter itself
        base, adapter = split_adapter(model, ctx.router.models())
        if adapter:
            span.set_attribute("router.adapter", adapter)
        explain: dict = {}
        with ctx.tracer.start_span("router.pick", parent=span,
                                   attributes={"model": model}) as pick_span:
            worker = ctx.router.pick(base, affinity,
                                     prompt_text=prompt_text,
                                     explain=explain, adapter=adapter)
            for k, v in explain.items():
                pick_span.set_attribute(f"router.{k}", v)
            if worker is not None:
                pick_span.set_attribute("worker.url", worker.url)
        if worker is None:
            span.set_status("ERROR", f"no live worker for {model!r}")
            ctx.metrics.errors_total.inc(model=model, code="503")
            self._error(503, f"no live worker for model {model!r}",
                        "service_unavailable")
            return

        m = ctx.metrics
        m.requests_total.inc(model=model)
        t0 = time.monotonic()
        if ctx.nats is not None:
            try:
                # resolving the head frame proves a responder exists; only
                # failures BEFORE it (no responder / timeout) may fall back
                parts = _nats_proxy_parts(ctx, worker, path, body,
                                          trace_headers, deadline)
            except Exception as e:
                log.warning("NATS plane failed (%s); HTTP fallback to %s",
                            e, worker.url)
                span.add_event("nats_fallback", {"error": str(e)})
            else:
                span.set_attribute("transport", "nats")
                span.set_attribute("worker.url", worker.url)
                self._send_nats_response(parts, model, t0, exemplar=ex)
                return
        # bounded failover: a CONNECT-phase failure (refused / no route /
        # DNS) proves the request never reached a worker, so retrying the
        # next pick is safe; a worker 503 (draining / overloaded) shed
        # BEFORE any work started, so it fails over too — that is what
        # makes rolling restarts hitless. A read timeout means a worker
        # accepted and may be generating — retrying would duplicate the
        # generation, so it is terminal (504). 502 only when no live
        # worker accepts. Journal-eligible STREAMS go further: the SSE
        # relay journals delivered tokens and splices a continuation onto
        # the same stream after a mid-stream worker death
        # (docs/robustness.md "Recovery semantics").
        journal_on = recovery.journal_eligible(body)
        resp = None
        last_err: Optional[str] = None
        last_503: Optional[tuple] = None  # replayed if every pick sheds
        tried: List[str] = []
        breakers = ctx.router.breakers
        for attempt in range(3):
            if attempt:
                # exclude workers that already refused: the ledger and HRW
                # are deterministic, so an unexcluded re-pick would bounce
                # off the same dead worker three times
                worker = ctx.router.pick(base, affinity,
                                         prompt_text=prompt_text,
                                         exclude=tried, adapter=adapter)
                if worker is None:
                    break
                span.add_event("failover_repick",
                               {"attempt": attempt, "worker.url": worker.url})
            if deadline.expired:
                # a failover re-pick must not outlive the client's budget
                self._shed_deadline(span, "during failover", model)
                return
            span.set_attribute("transport", "http")
            span.set_attribute("worker.url", worker.url)
            dispatch_headers = deadline.propagate({
                "Content-Type": "application/json", **trace_headers})
            if journal_on:
                # ask the worker to interleave recovery-journal comments
                # with the stream (serving/recovery.py)
                dispatch_headers[recovery.JOURNAL_HEADER] = "1"
            req = urllib.request.Request(
                worker.url.rstrip("/") + path,
                data=raw,
                headers=dispatch_headers,
                method="POST",
            )
            try:
                faults.raise_point(
                    "frontend.connect_refused",
                    lambda m: urllib.error.URLError(ConnectionRefusedError(m)))
                # the socket timeout IS the remaining deadline — the former
                # hard-coded 600 s held a proxy slot long after any client
                # had given up
                resp = urllib.request.urlopen(req,
                                              timeout=deadline.timeout())
                breakers.record_success(worker.url)
                break
            except urllib.error.HTTPError as e:
                # the worker is alive and answered: a real API response,
                # not a routing failure
                breakers.record_success(worker.url)
                payload = e.read()
                if e.code == 503:
                    # a draining/overloaded worker sheds BEFORE any work
                    # starts (admission gate), so failing over is safe;
                    # the shed response is replayed only if every pick
                    # sheds. The worker stays registered — it is alive,
                    # and re-heartbeats its real state
                    span.add_event("worker_503_failover",
                                   {"worker.url": worker.url})
                    tried.append(worker.url)
                    last_err = f"worker {worker.url} shed 503"
                    last_503 = (payload,
                                e.headers.get("Content-Type",
                                              "application/json"),
                                e.headers.get("Retry-After"))
                    continue
                # anything else is a definitive answer — pass it through
                if e.code >= 500:
                    ctx.metrics.errors_total.inc(model=model,
                                                 code=str(e.code))
                self.send_response(e.code)
                self.send_header(
                    "Content-Type",
                    e.headers.get("Content-Type", "application/json"))
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
                return
            except (urllib.error.URLError, socket.error) as e:
                reason = getattr(e, "reason", e)
                if isinstance(reason, (TimeoutError, socket.timeout)):
                    breakers.record_failure(worker.url)
                    ctx.deadline_shed.inc()
                    ctx.metrics.errors_total.inc(model=model, code="504")
                    span.set_status("ERROR", "worker timeout")
                    self._error(
                        504, f"worker {worker.url} timed out mid-request "
                        f"(deadline budget {deadline.budget_s:.1f}s)",
                        "timeout")
                    return
                if not net.pre_send_failure(e):
                    # connection lost AFTER the request was written: the
                    # worker may already be generating — a retry would
                    # duplicate the whole generation, so answer terminally
                    breakers.record_failure(worker.url)
                    ctx.metrics.errors_total.inc(model=model, code="502")
                    span.set_status("ERROR", "worker connection lost")
                    self._error(
                        502,
                        f"worker {worker.url} connection lost after the "
                        "request was sent; not retried",
                        "bad_gateway")
                    return
                log.warning("worker %s unreachable (%s); failing over",
                            worker.url, e)
                breakers.record_failure(worker.url)
                ctx.router.deregister(worker.url)
                # belt and braces with the deregister: a racing heartbeat
                # could re-register the dead worker before the re-pick
                tried.append(worker.url)
                last_err = str(e)
        if resp is None:
            if last_503 is not None:
                # every live pick shed 503 (cluster-wide drain/overload):
                # replay the worker's own shed response, Retry-After
                # jitter included, rather than escalating to 502
                payload, p_ctype, retry_after = last_503
                span.set_status("ERROR", "all workers shed 503")
                ctx.metrics.errors_total.inc(model=model, code="503")
                self.send_response(503)
                self.send_header("Content-Type", p_ctype)
                self.send_header("Content-Length", str(len(payload)))
                if retry_after:
                    self.send_header("Retry-After", retry_after)
                self.end_headers()
                self.wfile.write(payload)
                return
            span.set_status("ERROR", "no reachable worker")
            ctx.metrics.errors_total.inc(model=model, code="502")
            self._error(
                502,
                f"no reachable worker for model {model!r}"
                + (f" (last error: {last_err})" if last_err else ""),
                "bad_gateway")
            return
        if attempt:
            # connect-phase recovery: an earlier pick failed pre-send and
            # the re-pick carried the request
            ctx.recovered_counter.inc(phase="connect")

        ctype = resp.headers.get("Content-Type", "application/json")
        if "text/event-stream" in ctype:
            self._relay_sse(resp, worker, path, body, prompt_text,
                            affinity, model, span, trace_headers, deadline,
                            tried, attempt, journal_on, t0,
                            base=base, adapter=adapter)
        else:
            try:
                payload = resp.read()
            except (socket.error, OSError, http.client.HTTPException) as e:
                # worker connection died between its headers and its body:
                # the generation may have run — terminal, never retried
                span.set_status("ERROR", "worker connection lost mid-response")
                ctx.router.breakers.record_failure(worker.url)
                ctx.metrics.errors_total.inc(model=model, code="502")
                self._error(
                    502,
                    f"worker {worker.url} connection lost mid-response "
                    f"({type(e).__name__}); not retried", "bad_gateway")
                return
            m.ttft.observe(time.monotonic() - t0, exemplar=ex, model=model)
            m.tenant_ttft.observe(time.monotonic() - t0,
                                  tenant=self._tenant)
            try:
                usage = json.loads(payload).get("usage", {})
                m.isl.observe(usage.get("prompt_tokens", 0), model=model)
                m.osl.observe(usage.get("completion_tokens", 0), model=model)
            except Exception:
                pass
            self.send_response(resp.status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(payload)))
            # recovery observability: how many dispatches this response
            # took, and whether a failover carried it
            self.send_header("x-request-attempts", str(attempt + 1))
            if attempt:
                self.send_header("x-recovered", "1")
            self.end_headers()
            self.wfile.write(payload)
        m.duration.observe(time.monotonic() - t0, exemplar=ex, model=model)

    # --------------------------------------------- cross-frontend resume --
    def _resume_stream(self, path: str, body: dict, prompt_text: str,
                       affinity: str, model: str, span, trace_headers: dict,
                       deadline: Deadline) -> None:
        """Resume a stream whose original frontend died (serving/ha.py).

        The client re-POSTs its ORIGINAL request body plus a
        ``dynamo_resume`` key naming the response id and how many content
        chars it already received. Any frontend replica can serve it: the
        replicated journal plane holds the seam cursor, so the surviving
        frontend claims the resume (single winner fleet-wide), re-picks a
        worker preferring journaled-prefix KV overlap, and dispatches a
        PR 4 continuation — the worker re-emits exactly the chars past the
        client's cursor, byte-identical for greedy/seeded streams."""
        ctx = self.ctx
        plane = ctx.journal_plane

        def refuse(code: int, outcome: str, msg: str, etype: str) -> None:
            ctx.ha_resumes.inc(outcome=outcome)
            if code >= 500:
                ctx.metrics.errors_total.inc(model=model, code=str(code))
            span.set_status("ERROR", f"resume refused: {outcome}")
            span.set_attribute("resume.outcome", outcome)
            self._error(code, msg, etype)

        if plane is None:
            ctx.ha_resumes.inc(outcome="invalid")
            raise proto.BadRequest(
                "stream resume requires the replicated journal plane "
                "(frontend started without --nats-url)")
        try:
            spec = ha.normalize_resume(body.get(ha.RESUME_BODY_KEY))
        except ValueError as e:
            ctx.ha_resumes.inc(outcome="invalid")
            raise proto.BadRequest(f"bad {ha.RESUME_BODY_KEY}: {e}")
        rid, delivered = spec["response_id"], spec["delivered_chars"]
        span.set_attribute("resume.response_id", rid)
        rec = plane.lookup(rid)
        if rec is None:
            refuse(404, "unknown",
                   f"no replicated journal for response {rid!r} "
                   "(expired, never journaled, or a different cluster)",
                   "not_found")
            return
        if rec.done:
            refuse(409, "completed",
                   f"response {rid!r} already delivered its [DONE]; "
                   "nothing to resume", "conflict")
            return
        if not rec.resumable:
            refuse(409, "invalid",
                   f"journal for response {rid!r} is not resumable "
                   "(inconsistent checkpoint sequence)", "conflict")
            return
        if delivered > rec.checkpoint_chars:
            # the replicated journal is BEHIND what the client saw: a
            # continuation from this cursor would re-sample the gap —
            # refuse rather than risk duplicated or diverging output
            refuse(409, "stale_cursor",
                   f"replicated journal for {rid!r} is behind the client "
                   f"({rec.checkpoint_chars} < {delivered} chars); "
                   "cannot resume without risking duplicate output",
                   "conflict")
            return
        if not plane.claim(rid):
            refuse(409, "lost_claim",
                   f"another frontend won the resume claim for {rid!r}; "
                   "retry there or wait", "conflict")
            return
        # pre-seed a journal at the replicated seam; the relay's own
        # accounting continues from the client's cursor, and the worker's
        # continuation checkpoints (cumulative n) extend it consistently
        journal = recovery.RequestJournal(enabled_=True)
        journal.tokens = list(rec.tokens)
        journal.delivered_chars = delivered
        journal.checkpoint_chars = rec.checkpoint_chars
        journal.data_seen = True  # the client already holds the role chunk
        journal.response_id = rec.rid
        journal.seed = rec.seed
        journal.resume_key = (list(rec.resume_key)
                              if rec.resume_key else None)

        clean = {k: v for k, v in body.items()
                 if k != ha.RESUME_BODY_KEY}
        base, adapter = split_adapter(model, ctx.router.models())
        m = ctx.metrics
        m.requests_total.inc(model=model)
        t0 = time.monotonic()
        tried: List[str] = []
        resp = None
        worker = None
        attempt = 0
        for attempt in range(recovery.MAX_ATTEMPTS):
            if deadline.expired:
                plane.release_claim(rid)
                self._shed_deadline(span, "during resume", model)
                return
            worker = ctx.router.pick(base or model, affinity,
                                     prompt_text=prompt_text,
                                     exclude=tried, relaxed_overlap=True,
                                     adapter=adapter)
            if worker is None:
                break
            cont = dict(clean)
            cont[recovery.RECOVERY_BODY_KEY] = journal.continuation()
            headers = deadline.propagate({
                "Content-Type": "application/json",
                recovery.JOURNAL_HEADER: "1", **trace_headers})
            req = urllib.request.Request(
                worker.url.rstrip("/") + path,
                data=json.dumps(cont).encode(), headers=headers,
                method="POST")
            try:
                resp = urllib.request.urlopen(req,
                                              timeout=deadline.timeout())
                ctx.router.breakers.record_success(worker.url)
                break
            except urllib.error.HTTPError as e:
                e.read()
                ctx.router.breakers.record_success(worker.url)
                tried.append(worker.url)
            except (urllib.error.URLError, socket.error):
                ctx.router.breakers.record_failure(worker.url)
                tried.append(worker.url)
        if resp is None:
            plane.release_claim(rid)
            refuse(503, "no_worker",
                   f"no healthy worker to resume response {rid!r}",
                   "service_unavailable")
            return
        ctx.ha_resumes.inc(outcome="resumed")
        span.set_attribute("resume.outcome", "resumed")
        span.add_event("stream_resumed", {
            "response_id": rid, "worker.url": worker.url,
            "seam_token_index": journal.seam_token_index})
        self._relay_sse(resp, worker, path, clean, prompt_text, affinity,
                        model, span, trace_headers, deadline, tried,
                        attempt, True, t0, base=base, adapter=adapter,
                        journal=journal)
        m.duration.observe(time.monotonic() - t0, model=model)

    # ----------------------------------------------- mid-stream recovery --
    def _relay_sse(self, resp, worker, path: str, body: dict,
                   prompt_text: str, affinity: str, model: str, span,
                   trace_headers: dict, deadline: Deadline,
                   tried: List[str], attempt: int, journal_on: bool,
                   t0: float, base: Optional[str] = None,
                   adapter: Optional[str] = None,
                   journal: Optional[recovery.RequestJournal] = None,
                   ) -> None:
        """SSE relay with mid-stream recovery (serving/recovery.py).

        The worker stream is parsed into event blocks instead of being
        byte-proxied: ``dynr`` journal comments feed the per-request
        RequestJournal and are stripped; data frames are re-framed to the
        client verbatim. On a mid-stream failure (in-stream error event,
        reset, stall timeout, EOF without [DONE]) a healthy worker is
        re-picked — preferring ANY journaled-prefix KV overlap
        (router relaxed_overlap) — and the request is re-POSTed as a
        continuation; the worker re-emits exactly the chars past the
        seam, so greedy/seeded streams are byte-identical to a fault-free
        run. Non-journaled streams keep PR 2's truncate semantics."""
        ctx = self.ctx
        m = ctx.metrics
        # a cross-frontend resume arrives with a journal pre-seeded from
        # the replicated journal plane (serving/ha.py); everything else
        # starts from a blank one
        if journal is None:
            journal = recovery.RequestJournal(enabled_=journal_on)
        plane = ctx.journal_plane
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header("x-request-attempts", str(attempt + 1))
        if attempt:
            self.send_header("x-recovered", "1")
        self.end_headers()
        first = True
        t_prev: Optional[float] = None

        def forward(block: bytes) -> bool:
            nonlocal first, t_prev
            now = time.monotonic()
            ex = span.trace_id if span.recording else None
            if first:
                m.ttft.observe(now - t0, exemplar=ex, model=model)
                m.tenant_ttft.observe(now - t0, tenant=self._tenant)
                first = False
            elif t_prev is not None:
                # client-visible inter-token latency (includes relay +
                # network time the worker's own ITL histogram can't see)
                m.itl.observe(now - t_prev, exemplar=ex, model=model)
                m.tenant_itl.observe(now - t_prev, tenant=self._tenant)
            t_prev = now
            try:
                payload = block + b"\n\n"
                self.wfile.write(b"%x\r\n%s\r\n" % (len(payload), payload))
                self.wfile.flush()
                return True
            except (BrokenPipeError, ConnectionResetError, socket.error,
                    http.client.HTTPException, ValueError):
                return False

        def pump(stream):
            """Relay one worker stream. Returns (outcome, held_error):
            outcome in {"done", "client_gone", "failed"}."""
            for kind, block in recovery.iter_sse_blocks(stream):
                if kind != "block":
                    # conn/eof without [DONE]: the worker died (or handed
                    # off) mid-stream
                    return "failed", None
                bkind, extra = recovery.parse_block(block)
                if bkind == "journal":
                    journal.apply_comment(extra)
                    # HA: replicate the raw checkpoint to every peer
                    # frontend BEFORE the content it covers is forwarded,
                    # preserving the journal-runs-ahead seam invariant
                    # fleet-wide (a peer's copy is never behind what this
                    # frontend delivered at the time of the checkpoint)
                    if (plane is not None and journal.enabled
                            and journal.response_id):
                        plane.publish_record(journal.response_id, extra)
                elif bkind == "done":
                    return (("done", None) if forward(block)
                            else ("client_gone", None))
                elif bkind == "error":
                    # the worker reported its own death in-stream (crash
                    # mid-decode): hold the error — a successful splice
                    # makes it invisible to the client
                    return "failed", block
                else:
                    if not forward(block):
                        return "client_gone", None
                    if bkind == "data":
                        journal.on_data(extra)
            return "failed", None  # defensive: stream ended markerless

        outcome = "failed"
        held_error: Optional[bytes] = None
        while True:
            outcome, held_error = pump(resp)
            try:
                resp.close()
            except Exception:
                pass
            if outcome != "failed":
                break
            # ---- mid-stream failure: splice a continuation ----
            if journal.handoff:
                span.add_event("worker_handoff",
                               {"worker.url": worker.url,
                                "seam_token_index":
                                    journal.seam_token_index})
            resp = None
            while (journal.recoverable
                   and attempt + 1 < recovery.MAX_ATTEMPTS
                   and not deadline.expired):
                attempt += 1
                if worker.url not in tried:
                    tried.append(worker.url)
                explain: dict = {}
                nxt = ctx.router.pick(base or model, affinity,
                                      prompt_text=prompt_text,
                                      exclude=tried, explain=explain,
                                      relaxed_overlap=True, adapter=adapter)
                if nxt is None:
                    break
                worker = nxt
                cont = dict(body)
                cont[recovery.RECOVERY_BODY_KEY] = journal.continuation()
                headers = deadline.propagate({
                    "Content-Type": "application/json",
                    recovery.JOURNAL_HEADER: "1", **trace_headers})
                req = urllib.request.Request(
                    worker.url.rstrip("/") + path,
                    data=json.dumps(cont).encode(), headers=headers,
                    method="POST")
                try:
                    resp = urllib.request.urlopen(
                        req, timeout=deadline.timeout())
                    break
                except urllib.error.HTTPError as e:
                    # shed (503 draining) or rejected: spend the attempt
                    # and keep looking
                    e.read()
                    ctx.router.breakers.record_success(worker.url)
                    resp = None
                except (urllib.error.URLError, socket.error):
                    ctx.router.breakers.record_failure(worker.url)
                    resp = None
            if resp is None:
                # recovery impossible: surface the failure the pre-
                # recovery way — forward the worker's own error event (or
                # say why) and terminate the stream
                span.set_status(
                    "ERROR", "worker stream failed; not recovered")
                if held_error is not None:
                    forward(held_error)
                elif journal.enabled:
                    forward(b"data: " + json.dumps({"error": {
                        "message": "worker lost mid-stream; recovery "
                                   "failed (no healthy worker in budget)",
                        "type": "stream_error"}}).encode())
                if held_error is not None or journal.enabled:
                    forward(b"data: [DONE]")
                break
            # spliced: the continuation now feeds the SAME client stream
            ctx.recovered_counter.inc(phase="stream")
            span.add_event("stream_recovered", {
                "worker.url": worker.url, "attempt": attempt,
                "seam_token_index": journal.seam_token_index})
            span.set_attribute("recovery.seam_token_index",
                               journal.seam_token_index)
            span.set_attribute("worker.url", worker.url)
        if (plane is not None and journal.enabled and journal.response_id
                and outcome == "done"):
            # tombstone only on a [DONE] delivered to the client — a
            # client that vanished mid-stream must still be able to
            # resume through any peer frontend
            plane.publish_done(journal.response_id)
        try:
            self.wfile.write(b"0\r\n\r\n")
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, socket.error,
                http.client.HTTPException, ValueError):
            pass
        # the shared _route_and_forward tail observes request duration


def _nats_proxy_parts(ctx, worker, path, body, trace_headers=None,
                      deadline: Optional[Deadline] = None):
    from dynamo_tpu.serving import nats_plane

    headers = dict(trace_headers or {})
    timeout = 600.0
    if deadline is not None:
        deadline.propagate(headers)  # budget rides the NATS msg headers too
        timeout = deadline.timeout()
    return nats_plane.nats_request(
        ctx.nats, nats_plane.worker_subject(worker.url), path, body,
        timeout=timeout, trace_headers=headers,
    )


# split out so _proxy's HTTP path stays exactly as-is
def make_frontend_server(ctx: FrontendContext, host="0.0.0.0", port=8000):
    return make_http_server(_FrontendHandler, {"ctx": ctx}, host, port)
