"""Decode-worker side of disaggregated serving.

Request flow (mirror of the reference's disagg topology,
/root/reference/examples/deploy/sglang/disagg.yaml): the frontend routes the
user request to a DECODE worker; the decode worker picks a PREFILL worker,
POSTs /disagg/prefill, pulls the KV over the bootstrap channel, imports it
into its own paged cache, and streams tokens from there.
"""

from __future__ import annotations

import hashlib
import json
import logging
import socket
import threading
import time
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

from dynamo_tpu.engine.request import GenRequest, TokenEvent
from dynamo_tpu.observability import context as obs_context
from dynamo_tpu.observability import tracing as obs_tracing
from dynamo_tpu.robustness import deadline as ddl
from dynamo_tpu.robustness import faults
from dynamo_tpu.transfer.kv_transfer import fetch_kv
from dynamo_tpu.utils import net

log = logging.getLogger("dynamo_tpu.disagg")


def _trace_headers(span) -> Dict[str, str]:
    """HTTP headers carrying `span`'s context to the prefill worker (empty
    when tracing is off — the RPCs stay byte-identical to the untraced
    wire format)."""
    h: Dict[str, str] = {}
    ctx = getattr(span, "context", None)
    if ctx is not None:
        obs_context.inject_context(ctx, h)
    return h


class _StagedPullError(Exception):
    """Device pull failed AFTER the stage RPC pinned a gather remotely:
    the TCP fallback must still send /disagg/release or the prefill
    worker's stage-ledger slot (and the gathered HBM copy) leaks."""


class _PrefillUnreachable(Exception):
    """Connection-level failure BEFORE any KV moved (retry-safe)."""

    def __init__(self, reason: str):
        super().__init__(reason)
        self.reason = reason


class PrefillPool:
    """Known prefill workers: static (--prefill-url) plus frontend discovery."""

    def __init__(self, static_urls: Optional[List[str]] = None,
                 frontend_url: Optional[str] = None,
                 refresh_interval: float = 5.0):
        self._static = [u.strip() for u in (static_urls or []) if u.strip()]
        self._discovered: List[str] = []
        # HA frontend plane: frontend_url may name N replicas
        # (comma-separated); discovery asks each in turn until one answers
        # — every replica's registry is complete on its own
        self._frontend_urls = [u.strip() for u in (frontend_url or "").split(",")
                               if u.strip()]
        self._lock = threading.Lock()
        if frontend_url:
            t = threading.Thread(target=self._refresh_loop,
                                 args=(refresh_interval,), daemon=True,
                                 name="prefill-discovery")
            t.start()

    def _refresh_loop(self, interval: float):
        while True:
            for fe in self._frontend_urls:
                try:
                    with urllib.request.urlopen(
                        fe.rstrip("/") + "/internal/workers",
                        timeout=5,
                    ) as resp:
                        workers = json.loads(resp.read())["workers"]
                    urls = [w["url"] for w in workers
                            if w.get("mode") == "prefill"]
                    with self._lock:
                        self._discovered = urls
                    break
                except Exception as e:
                    log.debug("prefill discovery via %s failed: %s", fe, e)
            time.sleep(interval)

    def urls(self) -> List[str]:
        with self._lock:
            return list(dict.fromkeys(self._static + self._discovered))

    def pick(self, affinity_key: str, exclude=()) -> Optional[str]:
        urls = [u for u in self.urls() if u not in exclude]
        if not urls:
            return None
        best, best_score = None, -1
        for u in urls:
            h = hashlib.sha256((affinity_key + "|" + u).encode()).digest()
            score = int.from_bytes(h[:8], "big")
            if score > best_score:
                best, best_score = u, score
        return best


class DisaggDecodeClient:
    """Runs the prefill RPC + KV pull + import for one request."""

    PLANES = ("ici_inproc", "ici_device", "dcn")

    def __init__(self, ctx, pool: PrefillPool):
        self.ctx = ctx  # ServingContext
        self.pool = pool
        self._device_client = None
        self._dcn_warned: set = set()
        # per-plane COMPLETED-transfer counts (thread-safe labeled Counter,
        # scraped at /metrics and mirrored in /worker/stats): an ici
        # deployment that degrades to dcn is visible operationally
        from dynamo_tpu.serving.metrics import Counter

        self._plane_counter = Counter(
            "dynamo_worker_kv_transfers_total",
            "Completed disagg KV transfers by data plane",
            ctx.metrics.registry, labelnames=("plane",))

    @property
    def plane_counts(self) -> dict:
        vals = {p: 0 for p in self.PLANES}
        with self._plane_counter._lock:
            for lbl, v in self._plane_counter._values.items():
                vals[dict(lbl)["plane"]] = int(v)
        return vals

    def _warn_dcn_fallback(self, prefill_url: str, why: str):
        """--disaggregation-transfer-backend ici was requested but this pair
        degrades to the TCP plane: say so LOUDLY, once per pair (an operator
        deploying ici across pods must see the downgrade, not discover it in
        a bandwidth profile)."""
        if prefill_url in self._dcn_warned:
            return
        self._dcn_warned.add(prefill_url)
        log.warning(
            "ici transfer backend: prefill %s %s — falling back to the dcn "
            "(TCP host-bounce) plane for this pair", prefill_url, why)

    def start(self, req: GenRequest, parent_span=None,
              deadline: Optional[ddl.Deadline] = None) -> "object":
        """Returns the event queue, with the first token already delivered.

        Bounded prefill failover: an UNREACHABLE prefill worker (connection
        refused / dropped before any KV moved) is retried on the pool's
        next rendezvous pick; definitive rejections (400) and mid-transfer
        failures stay terminal.

        `parent_span` (the decode worker's request span) parents the
        disagg.prefill_rpc / disagg.kv_pull / disagg.kv_release spans and
        its trace context rides the prefill RPCs as HTTP headers.
        `deadline` (the request's remaining budget) bounds the prefill RPC
        and rides it as the x-deadline header."""
        if parent_span is None:
            parent_span = obs_tracing.NOOP_SPAN
        affinity = "".join(map(str, req.prompt_token_ids[:64]))
        tried: list = []
        while True:
            if deadline is not None and deadline.expired:
                raise TimeoutError(
                    "deadline budget exhausted before prefill dispatch")
            prefill_url = self.pool.pick(affinity, exclude=tried)
            if prefill_url is None:
                if tried:
                    raise RuntimeError(
                        f"prefill workers unreachable: {', '.join(tried)}")
                raise RuntimeError("no prefill worker available")
            try:
                return self._start_on(req, prefill_url, parent_span,
                                      deadline)
            except _PrefillUnreachable as e:
                log.warning("prefill %s unreachable (%s); failing over",
                            prefill_url, e.reason)
                tried.append(prefill_url)
                if len(tried) >= 3:
                    raise RuntimeError(
                        f"prefill workers unreachable: {', '.join(tried)}"
                    ) from e

    def _start_on(self, req: GenRequest, prefill_url: str,
                  parent_span=obs_tracing.NOOP_SPAN,
                  deadline: Optional[ddl.Deadline] = None) -> "object":
        ctx = self.ctx
        if ctx.engine.cfg.disaggregation_transfer_backend == "ici":
            from dynamo_tpu.transfer import ici_registry

            local = ici_registry.lookup(prefill_url)
            if local is not None:
                return self._start_ici(req, local, prefill_url, parent_span)

        body = json.dumps({
            "request_id": req.request_id,
            "prompt_token_ids": req.prompt_token_ids,
            "temperature": req.temperature,
            "top_p": req.top_p,
            "top_k": req.top_k,
            "min_p": req.min_p,
            "logit_bias": req.logit_bias,
            # seeded requests must sample the same first token the agg path
            # would (the prefill worker continues the request's key chain)
            "seed": req.seed,
            "logprobs": req.logprobs,
            # the prefill worker samples the FIRST token, so the grammar
            # mask must apply there too
            "guided_json": req.guided_json,
            # multi-LoRA: prefill must run under the same adapter weights
            # the decode side will attach
            "adapter": req.adapter,
            # per-tenant QoS: the prefill worker's spans/metrics carry the
            # same tenant identity the decode side resolved
            "tenant": req.tenant,
        }).encode()
        t0 = time.monotonic()
        rpc_span = ctx.tracer.start_span(
            "disagg.prefill_rpc", parent=parent_span, kind="client",
            attributes={"prefill.url": prefill_url,
                        "request.id": req.request_id,
                        "prompt_tokens": len(req.prompt_token_ids)})
        try:
            out = self._prefill_rpc(prefill_url, body, rpc_span, deadline)
        except BaseException as e:
            rpc_span.set_status("ERROR", f"{type(e).__name__}: {e}")
            rpc_span.end()
            raise
        rpc_span.set_attribute("n_tokens", int(out.get("n_tokens", 0)))
        rpc_span.end()
        # phase 2 — the KV pull. The prefill side now holds parked pages;
        # failures here are terminal for this request (the parked-KV TTL
        # sweep reclaims the pages), never silently retried elsewhere.
        pull_span = ctx.tracer.start_span(
            "disagg.kv_pull", parent=parent_span, kind="client",
            attributes={"prefill.url": prefill_url,
                        "request.id": req.request_id})
        first_token = out["first_token"]
        host = urllib.parse.urlparse(prefill_url).hostname
        released = False
        staged_ok = False  # stage RPC pinned a gather on the prefill side
        k = None
        want_ici = (
            ctx.engine.cfg.disaggregation_transfer_backend == "ici")
        if want_ici and out.get("device_transfer"):
            try:
                # cross-process device-buffer pull (no host bounce):
                # stage RPC + direct pull from the peer's device memory
                k, v = self._pull_device(prefill_url, host, req.request_id,
                                         pull_span)
                n_tokens = out["n_tokens"]
                self._plane_counter.inc(plane="ici_device")
            except _StagedPullError as e:
                staged_ok = True
                pull_span.add_event("device_pull_failed", {"error": str(e)})
                self._warn_dcn_fallback(
                    prefill_url, f"device-buffer pull failed ({e})")
            except Exception as e:
                pull_span.add_event("device_pull_failed", {"error": str(e)})
                self._warn_dcn_fallback(
                    prefill_url, f"device-buffer pull failed ({e})")
        elif want_ici:
            self._warn_dcn_fallback(
                prefill_url,
                "is neither in-process nor advertising device-buffer "
                "transfer")
        if k is None:
            try:
                k, v, n_tokens = fetch_kv(host, out["bootstrap_port"],
                                          req.request_id)
            except (ConnectionError, OSError) as e:
                pull_span.set_status("ERROR", str(e))
                pull_span.end()
                # the pull died with the prefill KV still parked: release
                # it NOW (best-effort; the TTL sweep remains the backstop)
                # so a frontend-recovered continuation re-prefilling under
                # the same request id never races a stale park — a
                # decode-side failure must leave the ledger balanced
                self._release_remote(prefill_url, req.request_id,
                                     parent_span)
                raise RuntimeError(
                    f"KV transfer from {prefill_url} failed: {e}") from e
            released = True  # the TCP plane acks (and releases) in-stream
            self._plane_counter.inc(plane="dcn")
        pull_span.set_attributes({
            "plane": "dcn" if released else "ici_device",
            "bytes": int(k.nbytes + v.nbytes),
            "n_tokens": int(n_tokens),
        })
        pull_span.end()
        log.info(
            "disagg%s: prefill(%d tok)+transfer(%.1f MB) in %.3fs via %s",
            "" if released else "[ici-device]", n_tokens,
            (k.nbytes + v.nbytes) / 1e6, time.monotonic() - t0,
            prefill_url,
        )

        q = ctx.service.attach(req.request_id)
        try:
            finished, reason = ctx.engine.import_kv(req, first_token, k, v)
        except Exception:
            ctx.service.detach(req.request_id)
            raise
        finally:
            # staged_ok + released: the TCP in-stream ack freed the parked
            # POOL pages but not the prefill side's stage-ledger slot (and
            # its pinned gather) — /disagg/release clears both and
            # engine.release_parked is idempotent for the already-freed
            # pages
            if not released or staged_ok:
                self._release_remote(prefill_url, req.request_id,
                                     parent_span)
        ev = TokenEvent(req.request_id, first_token, 0, finished, reason)
        if req.logprobs is not None and "logprob" in out:
            ev.logprob = out["logprob"]
            ev.top_logprobs = [tuple(t) for t in out.get("top_logprobs", [])]
        q.put(ev)
        ctx.service.wake()
        return q

    def _prefill_rpc(self, prefill_url: str, body: bytes, span,
                     deadline: Optional[ddl.Deadline] = None) -> dict:
        """Phase-1 prefill RPC. ONLY connection-phase failures here are
        retry-safe (no prefill ran, no KV parked anywhere); a read TIMEOUT
        means the worker accepted and may be computing, so a retry would
        duplicate the prefill — terminal instead. `span`'s trace context
        rides the request headers so the prefill worker's spans join this
        trace; the remaining deadline budget bounds the RPC (env-default
        budget when no deadline propagated — the former hard-coded 300 s)."""
        headers = {"Content-Type": "application/json",
                   **_trace_headers(span)}
        if deadline is not None:
            deadline.propagate(headers)
            timeout = deadline.timeout()
        else:
            timeout = ddl.default_budget_s()
        try:
            faults.raise_point(
                "disagg.prefill_connect_refused",
                lambda m: urllib.error.URLError(ConnectionRefusedError(m)))
            with urllib.request.urlopen(
                urllib.request.Request(
                    prefill_url.rstrip("/") + "/disagg/prefill", data=body,
                    headers=headers,
                    method="POST",
                ),
                timeout=timeout,
            ) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            # a definitive client error from the prefill side stays definitive
            # (400), so callers don't retry a request that can never succeed
            try:
                msg = json.loads(e.read())["error"]["message"]
            except Exception:
                msg = str(e)
            if e.code == 400:
                raise ValueError(f"prefill rejected request: {msg}") from e
            raise RuntimeError(
                f"prefill worker {prefill_url} failed ({e.code}): {msg}"
            ) from e
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            # only pre-send failures (refused / no route / DNS) are
            # retry-safe; a reset AFTER the request was written means the
            # worker may be mid-prefill and a retry would duplicate it and
            # park orphan KV — terminal, like timeouts
            if net.pre_send_failure(e):
                raise _PrefillUnreachable(str(e)) from e
            reason = getattr(e, "reason", e)
            if isinstance(reason, (TimeoutError, socket.timeout)):
                raise RuntimeError(
                    f"prefill worker {prefill_url} timed out mid-prefill"
                ) from e
            raise RuntimeError(
                f"prefill worker {prefill_url} connection lost after the "
                f"request was sent ({e}); not retried"
            ) from e

    def _pull_device(self, prefill_url: str, host: str, request_id: str,
                     span=obs_tracing.NOOP_SPAN):
        """Stage (RPC) then pull a parked sequence's KV via the jax transfer
        server (cross-process ici leg). A wildcard-bound advertised address
        is substituted with the prefill worker's URL host."""
        from dynamo_tpu.transfer.kv_transfer import DeviceKVClient

        if self._device_client is None:
            self._device_client = DeviceKVClient()
        with urllib.request.urlopen(
            urllib.request.Request(
                prefill_url.rstrip("/") + "/disagg/stage",
                data=json.dumps({"request_id": request_id}).encode(),
                headers={"Content-Type": "application/json",
                         **_trace_headers(span)},
                method="POST",
            ),
            timeout=30,
        ) as resp:
            staged = json.loads(resp.read())
        span.add_event("staged", {"transfer_address":
                                  staged.get("transfer_address", "?")})
        try:
            addr = staged["transfer_address"]
            bind_host, _, port = addr.rpartition(":")
            if bind_host.strip("[]") in ("", "::", "0.0.0.0"):
                addr = f"{host}:{port}"
            return self._device_client.pull(
                addr, staged["transfer_uuid"], staged["kv_shape"],
                staged["kv_dtype"])
        except Exception as e:
            # the stage RPC already pinned a gather remotely: the caller
            # must release it even though it falls back to the TCP plane
            raise _StagedPullError(str(e)) from e

    def _release_remote(self, prefill_url: str, request_id: str,
                        parent_span=obs_tracing.NOOP_SPAN) -> None:
        """Best-effort parked-page release after a device-buffer pull, on a
        background thread — the first token is already in hand and must not
        wait on cleanup (the prefill side's TTL sweep covers lost acks)."""
        def _post():
            span = self.ctx.tracer.start_span(
                "disagg.kv_release", parent=parent_span, kind="client",
                attributes={"prefill.url": prefill_url,
                            "request.id": request_id})
            try:
                urllib.request.urlopen(
                    urllib.request.Request(
                        prefill_url.rstrip("/") + "/disagg/release",
                        data=json.dumps({"request_id": request_id}).encode(),
                        headers={"Content-Type": "application/json",
                                 **_trace_headers(span)},
                        method="POST",
                    ),
                    timeout=10,
                ).close()
                span.set_status("OK")
            except Exception as e:
                span.set_status("ERROR", str(e))
                log.warning("parked-KV release on %s failed (%s); TTL sweep "
                            "will reclaim", prefill_url, e)
            span.end()

        threading.Thread(target=_post, daemon=True,
                         name="disagg-release").start()

    def _start_ici(self, req: GenRequest, prefill_engine, prefill_url: str,
                   parent_span=obs_tracing.NOOP_SPAN):
        """In-process (colocated) prefill: direct engine calls + the
        device-to-device KV handoff — no HTTP RPC, no TCP byte pump, no host
        copy of the pages (the NIXL->ICI reroute made real)."""
        ctx = self.ctx
        t0 = time.monotonic()
        with ctx.tracer.start_span(
                "disagg.prefill_rpc", parent=parent_span,
                attributes={"prefill.url": prefill_url,
                            "request.id": req.request_id,
                            "prompt_tokens": len(req.prompt_token_ids),
                            "plane": "ici_inproc"}) as rpc_span:
            first_token, n_tokens, extras = prefill_engine.prefill_only(req)
            rpc_span.set_attribute("n_tokens", int(n_tokens))
        with ctx.tracer.start_span(
                "disagg.kv_pull", parent=parent_span,
                attributes={"prefill.url": prefill_url,
                            "request.id": req.request_id,
                            "plane": "ici_inproc"}) as pull_span:
            k, v, _ = prefill_engine.export_kv_device(req.request_id)
            pull_span.set_attribute("n_tokens", int(n_tokens))
        self._plane_counter.inc(plane="ici_inproc")  # handoff data in hand
        q = ctx.service.attach(req.request_id)
        try:
            finished, reason = ctx.engine.import_kv(req, first_token, k, v)
        except Exception:
            ctx.service.detach(req.request_id)
            raise
        finally:
            prefill_engine.release_parked(req.request_id)
        log.info(
            "disagg[ici]: prefill(%d tok)+device handoff in %.3fs via %s",
            n_tokens, time.monotonic() - t0, prefill_url,
        )
        ev = TokenEvent(req.request_id, first_token, 0, finished, reason)
        if req.logprobs is not None and "logprob" in extras:
            ev.logprob = extras["logprob"]
            ev.top_logprobs = [tuple(t) for t in extras.get("top_logprobs", [])]
        q.put(ev)
        ctx.service.wake()
        return q
