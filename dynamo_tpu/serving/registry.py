"""etcd-backed worker registry sync.

The reference platform keeps its component registry in etcd
(/root/reference/install-dynamo-1node.sh:238-239, the reason the install
waits on dynamo-platform-etcd-0). Our workers heartbeat to the frontend over
HTTP; with multiple frontend replicas behind one Service, each replica only
sees the heartbeats the Service happens to route to it. This module closes
the gap: every frontend replica publishes its locally-heartbeated workers to
etcd under a shared prefix (lease-scoped so dead frontends' records expire)
and merges every replica's records back into its own Router.

Talks to etcd's v3 JSON/gRPC gateway (enabled by default on :2379 in the
platform StatefulSet, deploy/platform/etcd.yaml) with stdlib urllib only —
keys/values are base64 per the gateway contract. Registry failures degrade
to local-only discovery; they never take the frontend down.
"""

from __future__ import annotations

import base64
import json
import logging
import threading
import time
import urllib.request
from typing import Dict, List, Optional

log = logging.getLogger("dynamo_tpu.registry")


def _b64(s: str) -> str:
    return base64.b64encode(s.encode()).decode()


def _unb64(s: str) -> str:
    return base64.b64decode(s).decode()


class EtcdClient:
    """Minimal etcd v3 gateway client: lease grant/keepalive, put, range."""

    def __init__(self, endpoint: str, timeout: float = 5.0):
        self.endpoint = endpoint.rstrip("/")
        self.timeout = timeout

    def _call(self, path: str, body: dict) -> dict:
        req = urllib.request.Request(
            self.endpoint + path,
            data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return json.loads(resp.read() or b"{}")

    def grant_lease(self, ttl_s: int) -> int:
        out = self._call("/v3/lease/grant", {"TTL": ttl_s})
        return int(out["ID"])

    def keepalive(self, lease_id: int) -> bool:
        """True only if the lease is still alive — the gateway answers 200
        with an empty/zero-TTL result for an expired lease."""
        try:
            out = self._call("/v3/lease/keepalive", {"ID": lease_id})
            result = out.get("result") or {}
            return int(result.get("TTL", 0)) > 0
        except Exception:
            return False

    def delete(self, key: str):
        self._call("/v3/kv/deleterange", {"key": _b64(key)})

    def put(self, key: str, value: str, lease_id: Optional[int] = None):
        body = {"key": _b64(key), "value": _b64(value)}
        if lease_id:
            body["lease"] = lease_id
        self._call("/v3/kv/put", body)

    def range_prefix(self, prefix: str) -> Dict[str, str]:
        """All keys under prefix -> {key: value}."""
        end = prefix[:-1] + chr(ord(prefix[-1]) + 1)
        out = self._call(
            "/v3/kv/range", {"key": _b64(prefix), "range_end": _b64(end)}
        )
        kvs = out.get("kvs") or []
        return {_unb64(kv["key"]): _unb64(kv["value"]) for kv in kvs}


class EtcdRegistry:
    """Background sync between a Router and the shared etcd registry."""

    PREFIX = "/dynamo_tpu/workers/"

    def __init__(self, router, endpoint: str, ttl_s: int = 15,
                 interval_s: float = 3.0):
        self.router = router
        self.client = EtcdClient(endpoint)
        self.ttl_s = ttl_s
        self.interval_s = interval_s
        self._lease: Optional[int] = None
        self._published: set = set()  # keys this frontend currently owns
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- lifecycle
    def start(self):
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="etcd-registry"
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    # ------------------------------------------------------------- sync loop
    def _ensure_lease(self) -> Optional[int]:
        if self._lease is not None and self.client.keepalive(self._lease):
            return self._lease
        try:
            self._lease = self.client.grant_lease(self.ttl_s)
        except Exception as e:
            log.debug("etcd lease grant failed: %s", e)
            self._lease = None
        return self._lease

    def sync_once(self) -> int:
        """Publish directly-heartbeated workers, merge remote ones.

        Merged (peer-origin) workers are NEVER re-published — re-publishing
        would re-parent a dead worker's record under this frontend's live
        lease and resurrect it forever. Liveness is etcd lease expiry alone:
        the keepalive happens in this same loop that prunes dead workers, so
        a live lease implies a running sync loop implies pruned records. (A
        producer-wall-clock staleness check was dropped — cross-host clock
        skew > 2*ttl silently degraded discovery to local-only.) Returns the
        merged count."""
        lease = self._ensure_lease()
        if lease is None:
            return 0
        local = [
            w for w in self.router.alive(roles=("agg", "prefill", "decode"))
            if w.source == "direct"
        ]
        now = time.time()
        live_keys = set()
        for w in local:
            record = json.dumps({
                "url": w.url, "model": w.model, "mode": w.mode,
                "stats": w.stats, "ts": now,
            })
            key = self.PREFIX + w.url
            live_keys.add(key)
            try:
                self.client.put(key, record, lease)
                self._published.add(key)
            except Exception as e:
                log.debug("etcd put failed for %s: %s", w.url, e)
        # drop records for workers that stopped heartbeating here
        for key in list(self._published - live_keys):
            try:
                self.client.delete(key)
                self._published.discard(key)
            except Exception as e:
                log.debug("etcd delete failed for %s: %s", key, e)
        # merge peers' records
        merged = 0
        try:
            records = self.client.range_prefix(self.PREFIX)
        except Exception as e:
            log.debug("etcd range failed: %s", e)
            return 0
        known = {w.url for w in local}
        for _, raw in records.items():
            try:
                rec = json.loads(raw)
            except json.JSONDecodeError:
                continue
            if rec.get("url") in known:
                continue  # local heartbeats are fresher
            self.router.register(
                rec["url"], rec.get("model", "?"), rec.get("mode", "agg"),
                stats=rec.get("stats"), source="etcd",
            )
            merged += 1
        return merged

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.sync_once()
            except Exception as e:  # registry must never take the frontend down
                log.warning("etcd sync failed: %s", e)
