"""KV-affinity request router.

The reference's router lives in the consumed Dynamo runtime (Rust) and spreads
requests across worker replicas with KV-cache awareness (SURVEY.md §2b
"OpenAI-compatible frontend + router"). This implementation:

- **Rendezvous (HRW) hashing** on the prompt prefix: identical/shared prefixes
  deterministically land on the same worker, maximising paged-KV prefix reuse
  — without any shared state between frontend replicas.
- **Load shading**: the hash score is scaled by worker capacity headroom
  (free slots / free KV pages from heartbeats), so a hot worker sheds new
  prefixes to its peers.
- **Role filtering** for disaggregated topologies: chat traffic goes to
  `agg`/`decode` workers; `prefill` workers are selected separately by the
  decode worker's KV-fetch path (mirrors the reference's frontend→decode→
  prefill flow, /root/reference/examples/deploy/sglang/disagg.yaml).
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from typing import Dict, List, Optional, Tuple

from dynamo_tpu.robustness.breaker import BreakerBoard


@dataclasses.dataclass
class WorkerInfo:
    url: str
    model: str
    mode: str = "agg"  # agg | prefill | decode
    last_heartbeat: float = dataclasses.field(default_factory=time.monotonic)
    stats: Dict = dataclasses.field(default_factory=dict)
    # "direct" = heartbeated straight to this frontend; "etcd" = merged from
    # a peer replica's etcd registry record; "peer" = relayed by another
    # frontend over NATS worker gossip (serving/ha.py). Only direct workers
    # are re-published/relayed — non-direct records never loop.
    source: str = "direct"

    @property
    def headroom(self) -> float:
        """0..1 capacity signal from the last heartbeat."""
        s = self.stats or {}
        max_seqs = max(1, s.get("max_num_seqs", 1))
        active = s.get("active_seqs", 0) + s.get("pending", 0)
        slot_room = max(0.0, 1.0 - active / max_seqs)
        total_pages = max(1, s.get("total_pages", 1))
        page_room = s.get("free_pages", total_pages) / total_pages
        return 0.5 * slot_room + 0.5 * page_room

    @property
    def health(self) -> str:
        """Engine health from the heartbeat (robustness/watchdog.py state
        machine). Workers predating the watchdog field count healthy."""
        h = (self.stats or {}).get("health")
        if isinstance(h, dict):
            return h.get("state", "healthy")
        return h if isinstance(h, str) else "healthy"


def _pick_native(affinity_key: str, cands: List["WorkerInfo"]
                 ) -> Optional["WorkerInfo"]:
    """Run the pick loop in the native router core (runtime/csrc/
    dynamo_router.cpp — the Rust-frontend analogue); None = unavailable, let
    the caller's pure-Python loop decide. Scores are bit-identical either
    way (tests/test_router_native.py), so this is a pure hot-path swap."""
    try:
        from dynamo_tpu.runtime.native import get_router_lib
    except Exception:
        return None
    lib = get_router_lib()
    if lib is None:
        return None
    try:
        key = affinity_key.encode()
        urls = [w.url.encode() for w in cands]
        if b"\x00" in key or any(b"\x00" in u for u in urls):
            return None  # C strings truncate at NUL; keep parity via Python
        import ctypes

        arr = (ctypes.c_char_p * len(urls))(*urls)
        hr = (ctypes.c_double * len(cands))(*[w.headroom for w in cands])
        idx = lib.dr_pick(key, arr, hr, len(cands))
    except Exception:
        return None
    if 0 <= idx < len(cands):
        return cands[idx]
    return None


def prefix_key(text: str, prefix_chars: int = 256) -> str:
    """Affinity key: the first prefix_chars of the prompt (system prompt +
    early turns), which is what shared KV pages actually cover."""
    return text[:prefix_chars]


def split_adapter(model: str, live_models) -> Tuple[str, Optional[str]]:
    """'<base>:<adapter>' -> (base, adapter); plain base ids pass through.

    Matching is against the LIVE base-model set first (a base id could in
    principle contain ':'), falling back to splitting at the last colon so
    an adapter request can still 503 with a precise model name when no
    base worker is up."""
    if model in live_models:
        return model, None
    for m in live_models:
        if model.startswith(m + ":"):
            return m, model[len(m) + 1:]
    base, sep, adapter = model.rpartition(":")
    return (base, adapter) if sep else (model, None)


# ledger text-block geometry: 64-char blocks, 64-block hash window.
# pick()'s relative-overlap denominator derives from the same constants.
BLOCK_CHARS = 64
MAX_BLOCKS = 64


def text_block_chain(text: str, block_chars: int = BLOCK_CHARS,
                     max_blocks: int = MAX_BLOCKS) -> List[str]:
    """Rolling hash chain over fixed-size TEXT blocks of the prompt — the
    frontend-side analogue of the engine's page-block hash chain
    (engine/kv_cache.py PrefixCache). The frontend is tokenizer-free, so
    the chain is over canonical prompt text: a conversation continuation
    extends its previous turns' text, so its leading blocks hash
    identically and the deepest known block locates the worker whose
    paged-KV prefix cache already holds the shared turns (exact token
    matching stays the worker's job)."""
    out: List[str] = []
    prev = b""
    for i in range(0, min(len(text), block_chars * max_blocks), block_chars):
        block = text[i:i + block_chars]
        if len(block) < block_chars:
            break  # partial tail block can't be stable across turns
        h = hashlib.sha256(prev)
        h.update(block.encode("utf-8", "surrogatepass"))
        prev = h.digest()
        out.append(prev.hex())
    return out


class PrefixLedger:
    """block-hash -> worker url, LRU-capped: remembers where each prefix
    chain was routed so follow-up turns land on the worker that already
    holds the KV — the passive form of the reference router's KV-event
    tracking (SURVEY.md §2b: the Dynamo router scores workers by cached-
    block overlap from worker KV events; here the routing decision itself
    is the event, so frontends stay shared-nothing)."""

    def __init__(self, cap: int = 65536):
        import collections

        self.cap = cap
        self._m: "collections.OrderedDict[str, str]" = (
            collections.OrderedDict())

    def record(self, model: str, chain: List[str], url: str) -> None:
        for h in chain:
            key = model + "|" + h  # namespace: models sharing a prompt
            if key in self._m:     # template must not clobber each other
                self._m.move_to_end(key)
            self._m[key] = url
        while len(self._m) > self.cap:
            self._m.popitem(last=False)

    def lookup(self, model: str, chain: List[str],
               live_urls) -> Tuple[Optional[str], int]:
        """Deepest block whose recorded worker is still live.
        Returns (url, depth); (None, 0) when nothing matches."""
        for depth in range(len(chain), 0, -1):
            url = self._m.get(model + "|" + chain[depth - 1])
            if url is not None and url in live_urls:
                return url, depth
        return None, 0


class KVEventIndex:
    """Per-worker global prefix index built from worker-published KV events
    (dynamo_tpu.kvbm.events): block-hash -> {worker url -> tier}. Unlike
    the PrefixLedger — which only remembers where THIS frontend routed —
    the index reflects what workers actually hold (including blocks other
    frontend replicas routed, and blocks demoted to a worker's host tier),
    so it is pick()'s primary kv_overlap source; the ledger stays as the
    fallback when the event plane is down or cold.

    Event types: `stored` / `demoted` keep a block routable (device and
    host tiers both serve it — host onboards on lookup); `removed` drops
    the worker's claim. LRU-capped like the ledger."""

    def __init__(self, cap: int = 131072):
        import collections

        self.cap = cap
        self._m: "collections.OrderedDict[str, Dict[str, str]]" = (
            collections.OrderedDict())
        self._lock = threading.Lock()
        self.events_applied = 0

    def apply(self, payload: Dict) -> bool:
        """Apply one worker-published event payload (already-parsed JSON).
        Malformed payloads are dropped (False) — the plane is advisory."""
        try:
            kind = payload["type"]
            worker = payload["worker"]
            model = payload.get("model", "?")
            blocks = payload["blocks"]
            tier = payload.get("tier", "device")
        except (KeyError, TypeError):
            return False
        if kind not in ("stored", "demoted", "removed") or not isinstance(
                blocks, list):
            return False
        with self._lock:
            for b in blocks:
                key = model + "|" + str(b)
                holders = self._m.get(key)
                if kind == "removed":
                    if holders is not None:
                        holders.pop(worker, None)
                        if not holders:
                            del self._m[key]
                    continue
                if holders is None:
                    holders = self._m[key] = {}
                else:
                    self._m.move_to_end(key)
                holders[worker] = tier
            while len(self._m) > self.cap:
                self._m.popitem(last=False)
            self.events_applied += 1
        return True

    def drop_worker(self, url: str) -> None:
        """Forget a departed worker's claims (deregister/TTL purge)."""
        with self._lock:
            dead = [k for k, holders in self._m.items()
                    if holders.pop(url, None) is not None and not holders]
            for k in dead:
                del self._m[k]

    def lookup(self, model: str, chain: List[str], live_urls
               ) -> Tuple[Optional[str], int]:
        """Deepest block held by a live worker. Ties at equal depth go to
        the worker with the most headroom (live_urls maps url ->
        WorkerInfo). Returns (url, depth); (None, 0) on no match."""
        with self._lock:
            for depth in range(len(chain), 0, -1):
                holders = self._m.get(model + "|" + chain[depth - 1])
                if not holders:
                    continue
                alive = [u for u in holders if u in live_urls]
                if not alive:
                    continue
                best = max(alive, key=lambda u: live_urls[u].headroom)
                return best, depth
        return None, 0

    def stats(self) -> Dict:
        with self._lock:
            return {"entries": len(self._m),
                    "events_applied": self.events_applied}


class Router:
    def __init__(self, heartbeat_ttl: float = 15.0,
                 breakers: Optional[BreakerBoard] = None):
        self.ttl = heartbeat_ttl
        self._workers: Dict[str, WorkerInfo] = {}
        self._lock = threading.Lock()
        self._ledger = PrefixLedger()
        # KV event index (kvbm event plane): the PRIMARY kv_overlap source
        # when workers publish events; the ledger is the fallback
        self.kv_index = KVEventIndex()
        self.kv_index_hits = 0
        self.kv_index_counter = None  # optional metrics Counter
        self.ledger_hits = 0  # observability: KV-overlap routed requests
        # optional metrics Counter, inc'd at the routing decision itself
        # (under the router lock — scrape-time delta math would race
        # concurrent /metrics requests)
        self.ledger_counter = None
        # per-worker circuit breakers: pick() filters open breakers out of
        # the candidate set and admits the single half-open probe; the
        # frontend reports dial outcomes back via router.breakers
        self.breakers = breakers if breakers is not None else BreakerBoard()
        # workers whose heartbeat TTL lapsed and were purged during pick()
        self.expired_total = 0
        self.expired_counter = None  # optional metrics Counter

    # ---------------------------------------------------------- membership --
    def register(self, url: str, model: str, mode: str = "agg",
                 stats: Optional[Dict] = None, source: str = "direct"):
        with self._lock:
            w = self._workers.get(url)
            if w is None:
                self._workers[url] = WorkerInfo(url, model, mode,
                                                stats=stats or {},
                                                source=source)
                return
            if (source != "direct" and w.source == "direct"
                    and w.last_heartbeat >= time.monotonic() - self.ttl):
                # a live direct heartbeat is fresher than any peer's record
                # (etcd merge or NATS worker gossip); an expired one may be
                # resurrected by a peer that still hears the worker (e.g.
                # it re-registered elsewhere)
                return
            w.model, w.mode = model, mode
            w.source = source
            w.last_heartbeat = time.monotonic()
            if stats is not None:
                w.stats = stats

    def deregister(self, url: str):
        with self._lock:
            self._workers.pop(url, None)
        self.kv_index.drop_worker(url)

    def alive(self, roles=("agg", "decode"), model: Optional[str] = None
              ) -> List[WorkerInfo]:
        cutoff = time.monotonic() - self.ttl
        with self._lock:
            return [
                w for w in self._workers.values()
                if w.last_heartbeat >= cutoff and w.mode in roles
                and (model is None or w.model == model)
            ]

    def purge_expired(self) -> int:
        """Drop workers whose heartbeat TTL lapsed (alive() only FILTERS
        them; without this, a worker that died silently lingers in
        _workers forever and its expiry is invisible operationally).
        Called on every pick(); emits the worker_expired metric, labeled
        by the registration path whose refresh lapsed (reason="direct" is
        a worker that really went silent; reason="peer"/"etcd" means only
        the relay feeding this replica stopped — with NATS worker gossip
        up, a worker live ANYWHERE keeps every replica's last-seen fresh,
        so a one-frontend purge no longer churns fleet membership)."""
        cutoff = time.monotonic() - self.ttl
        with self._lock:
            dead = [(u, w.source) for u, w in self._workers.items()
                    if w.last_heartbeat < cutoff]
            for u, _src in dead:
                del self._workers[u]
            self.expired_total += len(dead)
            if dead and self.expired_counter is not None:
                for u, src in dead:
                    self.expired_counter.inc(reason=src)
        for u, _src in dead:
            self.kv_index.drop_worker(u)
        return len(dead)

    def models(self) -> List[str]:
        cutoff = time.monotonic() - self.ttl
        with self._lock:
            return sorted({
                w.model for w in self._workers.values()
                if w.last_heartbeat >= cutoff
            })

    def models_with_adapters(self) -> List[str]:
        """Base model ids plus one '<base>:<adapter>' entry per adapter any
        live worker can serve (resident or lazy-loadable) — the frontend's
        /v1/models surface."""
        cutoff = time.monotonic() - self.ttl
        out = set()
        with self._lock:
            for w in self._workers.values():
                if w.last_heartbeat < cutoff:
                    continue
                out.add(w.model)
                s = w.stats or {}
                for a in (s.get("adapters_available")
                          or s.get("adapters") or ()):
                    out.add(f"{w.model}:{a}")
        return sorted(out)

    # ------------------------------------------------------------- routing --
    def pick(self, model: str, affinity_key: str,
             roles=("agg", "decode"),
             prompt_text: Optional[str] = None,
             exclude=(),
             explain: Optional[Dict] = None,
             relaxed_overlap: bool = False,
             adapter: Optional[str] = None) -> Optional[WorkerInfo]:
        """`explain`, when given, is filled with the routing decision's
        inputs (candidate count, ledger depth/overlap, decision source) —
        the attributes the frontend's route-decision trace span records.

        `relaxed_overlap` is the recovery re-pick mode: a mid-stream
        failover re-dispatches prompt ⊕ emitted-tokens as a continuation
        prefill, so ANY worker holding even a shallow prefix of it (KV
        event index or ledger) beats the template-herding guardrail —
        the continuation's prefill cost is what the overlap offsets.

        `adapter` turns on adapter-affinity (multi-LoRA): workers
        advertising the adapter device-RESIDENT in their heartbeats win;
        with none resident, workers that can lazy-load it (host store)
        keep the request; failing that every base-model worker stays a
        candidate so stale stats can't strand the request. KV-overlap and
        HRW then run WITHIN the affinity set, and the prefix ledger/event
        index are keyed '<base>:<adapter>' so adapters never inherit each
        other's (or the base model's) routing history."""
        if explain is None:
            explain = {}
        self.purge_expired()
        cands = [w for w in self.alive(roles, model)
                 if w.url not in exclude]
        explain["candidates"] = len(cands)
        if cands:
            # circuit breakers: open breakers leave the candidate set (the
            # proactive form of the frontend's reactive failover); a
            # half-open breaker stays IN — being picked IS its probe
            allowed = [w for w in cands if self.breakers.would_allow(w.url)]
            skipped = len(cands) - len(allowed)
            if skipped:
                explain["breaker_skipped"] = skipped
            cands = allowed
        if cands:
            # engine watchdog: suspect/resurrecting/quarantined workers
            # advertise their health in heartbeats and leave the candidate
            # set — the proactive twin of their own 503 shed gate. A
            # quarantined worker would 503 every request anyway; skipping
            # it here saves the failover round trip.
            well = [w for w in cands if w.health == "healthy"]
            skipped = len(cands) - len(well)
            if skipped:
                explain["health_skipped"] = skipped
            cands = well
        if adapter and cands:
            explain["adapter"] = adapter
            resident = [w for w in cands
                        if adapter in ((w.stats or {}).get("adapters")
                                       or ())]
            if resident:
                cands = resident
                explain["adapter_affinity"] = "resident"
            else:
                lazy = [w for w in cands
                        if adapter in ((w.stats or {})
                                       .get("adapters_available") or ())]
                if lazy:
                    cands = lazy
                explain["adapter_affinity"] = "fallback_lazy_load"
        if not cands:
            # no worker serves this model -> let the frontend 503 rather than
            # bouncing the request off a wrong-model worker's 400
            return None
        # KV-overlap pass: follow the deepest prefix block we have routed
        # before, so multi-turn conversations keep landing on the worker
        # whose prefix cache holds their shared turns — even when HRW
        # load-shading diverted an earlier turn off the hash winner.
        # Guardrail against template-herding (every request sharing a
        # system prompt piling onto one worker): the overlap must be
        # RELATIVE — a true continuation shares most of its own chain
        # (its history IS the previous prompt), while an unrelated
        # request sharing only a system template matches a small leading
        # fraction however long the template is. Saturated holders still
        # shed to HRW (recompute beats queueing).
        chain = text_block_chain(prompt_text) if prompt_text else []
        # adapter requests key the routing history by '<base>:<adapter>' —
        # mirroring the engine's adapter-keyed prefix cache, so an
        # adapter's turns never herd onto a worker that only cached the
        # BASE model's KV for the same text
        ledger_model = f"{model}:{adapter}" if adapter else model
        if chain:
            live = {w.url: w for w in cands}
            # PRIMARY: the worker-published KV event index — real cache
            # contents (kvbm event plane), not this frontend's routing
            # history; the ledger covers cold/indexless prefixes
            url, depth = self.kv_index.lookup(ledger_model, chain, live)
            source = "kv_event_index"
            if url is None:
                with self._lock:
                    url, depth = self._ledger.lookup(ledger_model, chain,
                                                     live)
                source = "kv_overlap_ledger"
            # the ratio denominator uses the TRUE prompt length (capped at
            # the chain window) so a prompt longer than the hashed window
            # cannot make a long shared template look like majority
            # overlap; only a request whose entire hashed window is known
            # history clears the bar there
            denom = max(len(chain),
                        min(len(prompt_text) // BLOCK_CHARS, MAX_BLOCKS))
            explain["ledger_depth"] = depth
            explain["kv_overlap"] = round(depth / denom, 4) if denom else 0.0
            deep_enough = (depth >= 1 if relaxed_overlap
                           else depth >= 2 and depth * 10 >= 6 * denom)
            if relaxed_overlap:
                explain["recovery_repick"] = True
            if (url is not None and deep_enough
                    and live[url].headroom >= 0.05):
                with self._lock:
                    if source == "kv_event_index":
                        self.kv_index_hits += 1
                        if self.kv_index_counter is not None:
                            self.kv_index_counter.inc()
                    else:
                        self.ledger_hits += 1
                        if self.ledger_counter is not None:
                            self.ledger_counter.inc()
                    self._ledger.record(ledger_model, chain, url)
                explain["source"] = source
                explain["headroom"] = round(live[url].headroom, 4)
                return self._finish_pick(live[url], explain)
        picked = _pick_native(affinity_key, cands)
        explain["source"] = "hrw_native" if picked is not None else "hrw"
        if picked is None:
            best, best_score = None, -1.0
            for w in cands:
                h = hashlib.sha256(
                    (affinity_key + "|" + w.url).encode()
                ).digest()
                hash_score = int.from_bytes(h[:8], "big") / 2**64
                # weighted rendezvous: capacity scales the hash draw; a
                # worker with zero headroom can still win if it is the
                # only candidate
                score = hash_score * (0.25 + 0.75 * w.headroom)
                if score > best_score:
                    best, best_score = w, score
            picked = best
        if chain and picked is not None:
            with self._lock:
                self._ledger.record(ledger_model, chain, picked.url)
        if picked is not None:
            explain["headroom"] = round(picked.headroom, 4)
            return self._finish_pick(picked, explain)
        return picked

    def _finish_pick(self, picked: WorkerInfo, explain: Dict) -> WorkerInfo:
        """Common tail of every successful pick: consume the half-open
        probe slot (if any) and expose breaker state to the trace span."""
        self.breakers.on_picked(picked.url)
        explain["breaker"] = self.breakers.state(picked.url)
        return picked

    def pick_prefill(self, model: str, affinity_key: str) -> Optional[WorkerInfo]:
        return self.pick(model, affinity_key, roles=("prefill",))
