"""NATS request plane: frontend -> worker request transport.

Mirrors the reference platform's frontend/worker NATS plane
(/root/reference/install-dynamo-1node.sh:241-242; arch diagram
README.md:330-335). Subjects:

- `dynamo.req.worker.<worker-token>` — per-worker subject: the frontend's
  KV-affinity router picks the worker, NATS carries the request (the routed
  path; worker-token = sanitized advertised URL).
- `dynamo.req.model.<model-token>` — queue-group subject shared by every
  worker serving that model: router-less load balancing, one worker per
  request (NATS queue semantics), used when the frontend has no routing
  preference.

Wire format: the request payload is the raw OpenAI-API JSON body plus
"_path" (/v1/chat/completions or /v1/completions). The worker bridges the
message into its local HTTP handler (one loopback hop keeps a single code
path for parsing/streaming/metrics) and streams the response back on the
reply inbox as JSON frames:
    {"ack": true}                               (immediately on receipt)
    {"head": true, "status": N, "ctype": ...}   (once, before any body)
    {"c": <b64 chunk>}                          (0..n body chunks)
    {"done": true}                              (exactly once, last)
The ack decouples responder detection (fast, head_timeout) from head
arrival (a slow NON-streaming generation only sends its head once the
body is complete — that must not trip the no-responder fallback and
re-run inference over HTTP).
SSE bodies stream frame-by-frame, so frontend TTFT passthrough works the
same as the HTTP plane.
"""

from __future__ import annotations

import base64
import json
import logging
import threading
import urllib.error
import urllib.request
from typing import Iterator, Optional, Tuple

from dynamo_tpu.qos import tenancy as qos_tenancy
from dynamo_tpu.robustness import deadline as ddl
from dynamo_tpu.serving.nats import Msg, NatsClient, subject_token

log = logging.getLogger("dynamo_tpu.nats_plane")

WORKER_SUBJECT_PREFIX = "dynamo.req.worker"
MODEL_SUBJECT_PREFIX = "dynamo.req.model"
QUEUE_GROUP = "workers"


def worker_subject(worker_url: str) -> str:
    return f"{WORKER_SUBJECT_PREFIX}.{subject_token(worker_url)}"


def model_subject(model: str) -> str:
    return f"{MODEL_SUBJECT_PREFIX}.{subject_token(model)}"


class WorkerNatsPlane:
    """Worker-side responder: serve requests arriving over NATS by bridging
    them into the worker's own HTTP server."""

    def __init__(self, nats_url: str, self_http_url: str, model: str,
                 advertised_url: Optional[str] = None):
        self.http_url = self_http_url.rstrip("/")
        self.nc = NatsClient(nats_url, name=f"worker-{subject_token(model)}")
        self.nc.subscribe(worker_subject(advertised_url or self_http_url),
                          self._on_request)
        self.nc.subscribe(model_subject(model), self._on_request,
                          queue_group=QUEUE_GROUP)
        log.info("NATS request plane up: %s + %s (queue=%s)",
                 worker_subject(advertised_url or self_http_url),
                 model_subject(model), QUEUE_GROUP)

    def _on_request(self, msg: Msg) -> None:
        if not msg.reply:
            return
        # handler threads: inference streams can run for minutes
        threading.Thread(target=self._serve, args=(msg,), daemon=True,
                         name="nats-req").start()

    def _serve(self, msg: Msg) -> None:
        reply = msg.reply
        try:
            self.nc.publish(reply, b'{"ack": true}')
            body = json.loads(msg.data)
            path = body.pop("_path", "/v1/chat/completions")
            headers = {"Content-Type": "application/json"}
            # trace context AND the deadline budget rode the NATS message
            # headers (HPUB) — bridge them onto the loopback HTTP hop so
            # the worker's request span joins the frontend's trace and its
            # deadline keeps counting down
            inbound = msg.parsed_headers()
            for h in ("traceparent", "x-request-id", ddl.DEADLINE_HEADER,
                      qos_tenancy.RESOLVED_HEADER):
                if inbound.get(h):
                    headers[h] = inbound[h]
            deadline = ddl.Deadline.from_headers(headers)
            req = urllib.request.Request(
                self.http_url + path,
                data=json.dumps(body).encode(),
                headers=headers,
                method="POST",
            )
            try:
                resp = urllib.request.urlopen(req,
                                              timeout=deadline.timeout())
                status = resp.status
            except urllib.error.HTTPError as e:
                resp, status = e, e.code
            ctype = resp.headers.get("Content-Type", "application/json")
            self.nc.publish(reply, json.dumps(
                {"head": True, "status": status, "ctype": ctype}
            ).encode())
            while True:
                chunk = (resp.read1(32768) if hasattr(resp, "read1")
                         else resp.read(32768))
                if not chunk:
                    break
                self.nc.publish(reply, json.dumps(
                    {"c": base64.b64encode(chunk).decode()}
                ).encode())
            self.nc.publish(reply, json.dumps({"done": True}).encode())
        except Exception as e:
            log.exception("nats request failed")
            err = json.dumps({"error": {"message": str(e),
                                        "type": "internal_error"}})
            try:
                self.nc.publish(reply, json.dumps(
                    {"head": True, "status": 500,
                     "ctype": "application/json"}).encode())
                self.nc.publish(reply, json.dumps(
                    {"c": base64.b64encode(err.encode()).decode()}).encode())
                self.nc.publish(reply, json.dumps({"done": True}).encode())
            except Exception:
                pass

    def close(self) -> None:
        self.nc.close()


def nats_request(
    nc: NatsClient, subject: str, path: str, body: dict,
    timeout: float = 600.0, head_timeout: float = 5.0,
    trace_headers: Optional[dict] = None,
) -> Tuple[int, str, Iterator[bytes]]:
    """Frontend-side call: returns (status, content_type, chunk iterator).

    The first reply frame resolves status/ctype... frames carry body chunks
    until the done frame; chunks observed before done are yielded in order
    (for SSE, each frame lands as soon as the worker emits it).

    `trace_headers` (traceparent / x-request-id) ride as NATS message
    headers (HPUB), NOT in the JSON body — the request payload stays the
    raw OpenAI body and the context survives the plane the same way it
    survives HTTP.
    """
    payload = dict(body)
    payload["_path"] = path
    frames = nc.request_stream(subject, json.dumps(payload).encode(),
                               timeout=timeout, first_timeout=head_timeout,
                               headers=trace_headers or None)
    head = json.loads(next(frames).data)
    if head.get("ack"):  # responder exists; the head may take a while
        head = json.loads(next(frames).data)
    if not head.get("head"):
        raise ConnectionError(f"nats plane protocol error: {head}")
    status = int(head.get("status", 200))
    ctype = head.get("ctype", "application/json")

    def body_chunks() -> Iterator[bytes]:
        for msg in frames:
            frame = json.loads(msg.data)
            if "c" in frame:
                yield base64.b64decode(frame["c"])
            elif frame.get("done"):
                return

    return status, ctype, body_chunks()
