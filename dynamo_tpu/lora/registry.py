"""Host-resident LoRA adapter store with bounded device slots.

Two tiers, mirroring the KVBM posture for weights instead of KV:

- **Host store**: every registered adapter's rank-padded numpy stacks
  (`register()` from an `.npz` / HF-peft safetensors directory, or from
  in-memory tensors). Registration validates shapes and rank against the
  base model config, so a wrong-base adapter fails at load time, not with
  an opaque XLA shape error mid-request.
- **Device slots**: `EngineConfig.lora_slots` slots (1..S) inside the
  engine's stacked `[L, S, in, R]` LoRA params (slot 0 is the reserved
  all-zero base slot). `acquire_slot()` lazily loads an adapter into a
  free slot — or LRU-evicts a resident adapter no live sequence is using —
  with one `.at[:, slot].set()` scatter per matrix under the engine's
  exec lock, so swaps serialize against decode dispatches.

The serving layer exposes this through `GET/POST /v1/adapters` on workers
and advertises resident adapters in heartbeats for the router's
adapter-affinity pass.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import os
import re
import threading
from typing import Dict, List, Optional

import numpy as np

from dynamo_tpu.lora import apply as lora_apply

log = logging.getLogger("dynamo_tpu.lora")

_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


class NoFreeAdapterSlot(RuntimeError):
    """Every device slot is held by an adapter with live sequences."""


@dataclasses.dataclass
class HostAdapter:
    name: str
    rank: int
    alpha: float
    path: Optional[str]
    # target -> ('a': [L, in, Rmax], 'b': [L, Rmax, out]); the alpha/rank
    # scale is already folded into B, rank already padded to the engine max
    tensors: Dict[str, np.ndarray]


def save_adapter_npz(path: str, tensors: Dict[str, np.ndarray],
                     rank: int, alpha: Optional[float] = None) -> None:
    """Write an adapter directory in the repo-native layout: adapter.npz
    with keys '<t>a'/'<t>b' ([L, in, r] / [L, r, out]) + adapter_config.json
    carrying {r, lora_alpha}."""
    os.makedirs(path, exist_ok=True)
    np.savez(os.path.join(path, "adapter.npz"), **tensors)
    with open(os.path.join(path, "adapter_config.json"), "w") as f:
        json.dump({"r": rank, "lora_alpha": alpha if alpha is not None
                   else rank}, f)


def _load_adapter_dir(path: str):
    """-> (tensors {'<t>a'/'<t>b': [L, ...]}, rank, alpha). Supports the
    repo-native adapter.npz layout and HF-peft safetensors naming
    (`...layers.{i}.self_attn.{t}_proj.lora_{A,B}.weight`, stored
    [r, in] / [out, r] per layer)."""
    cfg_path = os.path.join(path, "adapter_config.json")
    rank, alpha = None, None
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            c = json.load(f)
        rank = c.get("r")
        alpha = c.get("lora_alpha")
    npz = os.path.join(path, "adapter.npz")
    if os.path.exists(npz):
        with np.load(npz) as z:
            tensors = {k: np.asarray(z[k]) for k in z.files}
        return tensors, rank, alpha
    st = os.path.join(path, "adapter_model.safetensors")
    if os.path.exists(st):
        from safetensors import safe_open

        per_layer: Dict[str, Dict[int, np.ndarray]] = {}
        layer_re = re.compile(
            r"layers\.(\d+)\.self_attn\.([qkvo])_proj\.lora_([AB])\.weight$")
        with safe_open(st, framework="numpy") as f:
            for key in f.keys():
                m = layer_re.search(key)
                if not m:
                    continue
                li, t, ab = int(m.group(1)), m.group(2), m.group(3)
                w = np.asarray(f.get_tensor(key), np.float32)
                # peft stores A [r, in] and B [out, r]; engine layout is
                # A [in, r], B [r, out]
                per_layer.setdefault(t + ab.lower(), {})[li] = w.T
        tensors = {}
        for k, by_layer in per_layer.items():
            layers = [by_layer[i] for i in sorted(by_layer)]
            tensors[k] = np.stack(layers, axis=0)
        if tensors:
            return tensors, rank, alpha
    raise ValueError(
        f"no adapter found under {path!r} (need adapter.npz or "
        f"adapter_model.safetensors)")


class LoRARegistry:
    """Per-engine adapter registry (engine.lora). Thread-safe: HTTP
    management threads and the scheduler's admission path both call it."""

    def __init__(self, engine):
        self.engine = engine
        cfg = engine.cfg
        mcfg = engine.model_cfg
        if mcfg.is_mla:
            raise ValueError(
                "multi-LoRA serving does not support MLA models yet (the "
                "absorbed-latent projections need a different placement)")
        self.max_rank = max(1, int(cfg.lora_rank))
        self.num_slots = int(cfg.lora_slots)
        self._host: Dict[str, HostAdapter] = {}
        # resident name -> device slot, in LRU order (oldest first)
        self._resident: "collections.OrderedDict[str, int]" = (
            collections.OrderedDict())
        self._free: List[int] = list(range(self.num_slots, 0, -1))
        self._lock = threading.RLock()
        self.swaps_total = 0  # device (re)loads of an adapter into a slot
        self.evictions_total = 0
        self.requests_total: Dict[str, int] = {}
        self._dims = lora_apply.target_dims(mcfg)
        # install the zeroed device stacks into the engine's param tree
        # (replicated across the mesh; the deltas are tiny next to the base
        # projections, and replication keeps the gathered einsum local)
        import jax
        import jax.numpy as jnp

        rep = jax.sharding.NamedSharding(engine.mesh,
                                         jax.sharding.PartitionSpec())
        dtype = jnp.dtype(mcfg.dtype)
        for name, shape in lora_apply.stack_shapes(
                mcfg, self.num_slots + 1, self.max_rank).items():
            engine.params[name] = jax.device_put(
                jnp.zeros(shape, dtype), rep)

    # ------------------------------------------------------------- host tier
    def register(self, name: str, path: Optional[str] = None,
                 tensors: Optional[Dict[str, np.ndarray]] = None,
                 rank: Optional[int] = None,
                 alpha: Optional[float] = None) -> HostAdapter:
        """Add (or replace) a host-store adapter from a directory or from
        in-memory tensors. Raises ValueError on bad names/shapes/ranks."""
        if not _NAME_RE.match(name or ""):
            raise ValueError(
                f"invalid adapter name {name!r} (alphanumeric plus ._- , "
                f"max 64 chars; ':' is the base/adapter separator)")
        if tensors is None:
            if not path:
                raise ValueError("need a path or tensors to register")
            tensors, file_rank, file_alpha = _load_adapter_dir(path)
            rank = rank if rank is not None else file_rank
            alpha = alpha if alpha is not None else file_alpha
        tensors = {k: np.asarray(v, np.float32) for k, v in tensors.items()}
        if rank is None:
            rank = next(iter(tensors.values())).shape[-1] \
                if tensors else self.max_rank
            for t in self._dims:
                if t + "a" in tensors:
                    rank = tensors[t + "a"].shape[-1]
                    break
        rank = int(rank)
        alpha = float(alpha) if alpha is not None else float(rank)
        scale = alpha / rank
        l = self.engine.model_cfg.num_layers
        padded: Dict[str, np.ndarray] = {}
        for t, (d_in, d_out) in self._dims.items():
            a, b = tensors.get(t + "a"), tensors.get(t + "b")
            if a is None and b is None:
                # untargeted projection: stays the zero delta
                continue
            if a is None or b is None:
                raise ValueError(f"adapter {name!r}: target {t!r} needs "
                                 f"both A and B matrices")
            if a.shape != (l, d_in, rank) or b.shape != (l, rank, d_out):
                raise ValueError(
                    f"adapter {name!r}: target {t!r} shapes "
                    f"A{a.shape}/B{b.shape} do not match the base model "
                    f"(want A{(l, d_in, rank)} / B{(l, rank, d_out)})")
            a, b = lora_apply.pad_rank(a, b * scale, self.max_rank)
            padded[t + "a"], padded[t + "b"] = a, b
        if not padded:
            raise ValueError(f"adapter {name!r} targets none of {list(self._dims)}")
        ad = HostAdapter(name, rank, alpha, path, padded)
        with self._lock:
            slot = self._resident.get(name)
            self._host[name] = ad
        if slot is not None:
            # re-registration replaces the weights: refresh the device copy
            self._write_slot(ad, slot)
        log.info("registered adapter %s (rank %d, alpha %g, targets %s)",
                 name, rank, alpha,
                 sorted({k[0] for k in padded}))
        return ad

    def unregister(self, name: str) -> None:
        self.unload(name)
        with self._lock:
            self._host.pop(name, None)

    def known(self, name: str) -> bool:
        with self._lock:
            return name in self._host

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._host)

    def resident(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._resident)

    def slot_of(self, name: str) -> Optional[int]:
        with self._lock:
            return self._resident.get(name)

    # ----------------------------------------------------------- device tier
    def _in_use_slots(self) -> set:
        """Slots pinned by live sequences (active batch + the in-flight
        chunked prefill). Pending requests are NOT pins: their admission
        re-acquires (and reloads if needed)."""
        eng = self.engine
        used = {getattr(s, "adapter_slot", 0) for s in eng.seqs.values()}
        inf = eng._inflight
        if inf is not None:
            used.add(getattr(inf, "aslot", 0))
        used.discard(0)
        return used

    def _write_slot(self, ad: HostAdapter, slot: int) -> None:
        """Scatter one adapter's stacks into device slot `slot` (serialized
        against decode dispatches by the engine exec lock)."""
        import jax.numpy as jnp

        eng = self.engine
        with eng._exec_lock:
            for t in self._dims:
                for w in ("a", "b"):
                    arr = ad.tensors.get(t + w)
                    pname = lora_apply.param_name(t, w)
                    stack = eng.params[pname]
                    if arr is None:
                        arr = np.zeros(stack.shape[0:1] + stack.shape[2:],
                                       np.float32)
                    eng.params[pname] = stack.at[:, slot].set(
                        jnp.asarray(arr, stack.dtype))
        self.swaps_total += 1

    def acquire_slot(self, name: str) -> int:
        """Resolve an adapter name to its device slot, lazily loading (and
        LRU-evicting an idle resident if every slot is taken). Raises
        KeyError for unregistered names, NoFreeAdapterSlot when all slots
        are pinned by live sequences."""
        with self._lock:
            slot = self._resident.get(name)
            if slot is not None:
                self._resident.move_to_end(name)
                return slot
            ad = self._host.get(name)
            if ad is None:
                raise KeyError(f"unknown adapter {name!r}")
            if self._free:
                slot = self._free.pop()
            else:
                pinned = self._in_use_slots()
                victim = next((n for n, s in self._resident.items()
                               if s not in pinned), None)
                if victim is None:
                    raise NoFreeAdapterSlot(
                        f"all {self.num_slots} adapter slots are serving "
                        f"live sequences; retry shortly")
                slot = self._resident.pop(victim)
                self.evictions_total += 1
                log.info("evicting adapter %s from slot %d for %s",
                         victim, slot, name)
            self._resident[name] = slot
        self._write_slot(ad, slot)
        log.info("loaded adapter %s into device slot %d", name, slot)
        return slot

    def unload(self, name: str) -> bool:
        """Drop an adapter's device slot (host copy stays registered).
        False when it wasn't resident; raises NoFreeAdapterSlot while live
        sequences still use it."""
        with self._lock:
            slot = self._resident.get(name)
            if slot is None:
                return False
            if slot in self._in_use_slots():
                raise NoFreeAdapterSlot(
                    f"adapter {name!r} is serving live sequences")
            del self._resident[name]
            self._free.append(slot)
        return True

    def note_request(self, name: str) -> None:
        with self._lock:
            self.requests_total[name] = self.requests_total.get(name, 0) + 1

    def stats(self) -> Dict:
        with self._lock:
            return {
                "slots_total": self.num_slots,
                "slots_free": len(self._free),
                "registered": sorted(self._host),
                "resident": dict(self._resident),
                "swaps_total": self.swaps_total,
                "evictions_total": self.evictions_total,
                "requests_total": dict(self.requests_total),
            }

    def describe(self) -> List[Dict]:
        """The GET /v1/adapters payload."""
        with self._lock:
            return [{
                "name": n,
                "rank": ad.rank,
                "alpha": ad.alpha,
                "path": ad.path,
                "resident": n in self._resident,
                "slot": self._resident.get(n),
                "requests": self.requests_total.get(n, 0),
            } for n, ad in sorted(self._host.items())]


def parse_adapter_list(spec: str) -> List:
    """'name=/path,other=/path2' (the DYNAMO_TPU_LORA_ADAPTERS /
    --lora-adapters form, materialized by the operator's `loraAdapters`
    manifest key) -> [(name, path)]."""
    out = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, path = part.partition("=")
        if not sep or not name or not path:
            raise ValueError(
                f"bad --lora-adapters entry {part!r} (want name=/path)")
        out.append((name.strip(), path.strip()))
    return out
