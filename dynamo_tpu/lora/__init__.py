"""Multi-LoRA adapter serving.

`apply` holds the batched in-engine LoRA math (stacked `[slots, r, d]`
device tensors, one gathered einsum per projection); `registry` holds the
host-resident adapter store with bounded device slots and LRU load/unload.
"""
