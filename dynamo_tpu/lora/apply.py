"""Batched in-engine LoRA application (the RTP-LLM-style multi-LoRA path).

Adapters are stacked into device tensors with a leading SLOT axis — for each
target projection `t` the engine's param tree carries

    lora_{t}a: [L, S, in,  R]   (the A matrices, rank-padded to R)
    lora_{t}b: [L, S, R,  out]  (the B matrices, alpha/rank scale folded in)

where L = num_layers (the scan axis the rest of the param tree already
carries), S = device adapter slots + 1 and R = the engine's max rank.
Slot 0 is the reserved BASE slot: its matrices are all-zero, so bare-base
requests ride the same fused program with a zero delta — a mixed-adapter
batch needs no per-adapter dispatch, masking, or batch splitting.

Each forward carries a per-sequence (per-token after broadcast) slot index
and applies

    y += (x @ A[s]) @ B[s]

as one gathered einsum pair per projection: the gather `A[slots]` /
`B[slots]` selects each token's adapter and XLA fuses the two small
contractions into the surrounding projection epilogue. Rank padding is
free correctness-wise — padded A columns are zero, so the extra lanes of
`x @ A[s]` contribute nothing through the (zero) padded B rows.

Targets are the attention projections q/k/v/o (the high-leverage LoRA
placement; MLP targets can stack on the same scheme later). MLA models are
rejected at engine init — their absorbed-latent projections need a
different placement.

The per-token slot broadcast is what lets speculative verify forwards run
through adapters (v2, docs/perf.md "Speculative decoding v2"): a K+1-wide
verify window repeats its sequence's slot index per window position
(llama.decode_verify / mixed_verify_step), so adapter sequences accept
drafts scored by their OWN weights — the round-3 base-logits fallback and
its acceptance penalty are gone.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

TARGETS = ("q", "k", "v", "o")


def param_name(target: str, which: str) -> str:
    """Engine param-tree key for a stacked LoRA matrix ('a' or 'b')."""
    return f"lora_{target}{which}"


STACK_NAMES = tuple(param_name(t, w) for t in TARGETS for w in ("a", "b"))


def target_dims(model_cfg) -> Dict[str, Tuple[int, int]]:
    """target -> (in_features, out_features) of the wrapped projection."""
    e = model_cfg.hidden_size
    h = model_cfg.num_heads * model_cfg.head_dim
    kv = model_cfg.num_kv_heads * model_cfg.head_dim
    return {"q": (e, h), "k": (e, kv), "v": (e, kv), "o": (h, e)}


def stack_shapes(model_cfg, slots: int, rank: int
                 ) -> Dict[str, Tuple[int, ...]]:
    """Shapes of the device stacks for `slots` TOTAL slots (incl. base 0)."""
    l = model_cfg.num_layers
    out = {}
    for t, (d_in, d_out) in target_dims(model_cfg).items():
        out[param_name(t, "a")] = (l, slots, d_in, rank)
        out[param_name(t, "b")] = (l, slots, rank, d_out)
    return out


def init_stacks(model_cfg, slots: int, rank: int,
                dtype=np.float32) -> Dict[str, np.ndarray]:
    """All-zero host stacks (slot 0 stays zero forever = the base slot)."""
    return {name: np.zeros(shape, dtype)
            for name, shape in stack_shapes(model_cfg, slots, rank).items()}


def delta(jnp, x, a_stack, b_stack, slots):
    """y-delta for one projection: x [T, in], a_stack [S, in, R] (one
    layer's slice), b_stack [S, R, out], slots [T] int32 -> [T, out].

    One gather + two small einsums; the gather is per-token so arbitrary
    adapter mixtures in one batch run fused."""
    a = a_stack[slots].astype(x.dtype)  # [T, in, R]
    b = b_stack[slots].astype(x.dtype)  # [T, R, out]
    u = jnp.einsum("ti,tir->tr", x, a)
    return jnp.einsum("tr,tro->to", u, b)


def pad_rank(a: np.ndarray, b: np.ndarray, rank: int
             ) -> Tuple[np.ndarray, np.ndarray]:
    """Zero-pad per-layer A [L, in, r] / B [L, r, out] up to max rank."""
    r = a.shape[-1]
    if r > rank:
        raise ValueError(f"adapter rank {r} exceeds the engine's "
                         f"--lora-rank {rank}")
    if r == rank:
        return a, b
    a2 = np.zeros(a.shape[:-1] + (rank,), a.dtype)
    a2[..., :r] = a
    b2 = np.zeros((b.shape[0], rank) + b.shape[2:], b.dtype)
    b2[:, :r] = b
    return a2, b2


def random_adapter(model_cfg, rank: int, seed: int = 0, scale: float = 0.05
                   ) -> Dict[str, np.ndarray]:
    """Seeded random adapter tensors (tests, smoke benches): per target,
    'ta'/'tb' with shapes [L, in, r] / [L, r, out]. Both sides nonzero so
    the delta is visible in greedy output immediately."""
    rng = np.random.default_rng(seed)
    l = model_cfg.num_layers
    out: Dict[str, np.ndarray] = {}
    for t, (d_in, d_out) in target_dims(model_cfg).items():
        out[t + "a"] = (rng.standard_normal((l, d_in, rank)) * scale
                        ).astype(np.float32)
        out[t + "b"] = (rng.standard_normal((l, rank, d_out)) * scale
                        ).astype(np.float32)
    return out
