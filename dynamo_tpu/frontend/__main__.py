"""Frontend entrypoint: OpenAI-compatible router over registered workers.

TPU-native stand-in for the Dynamo frontend pod every reference manifest
declares (/root/reference/examples/deploy/vllm/agg.yaml:12-17).
"""

import argparse
import logging
import os
import signal
import threading
import time

from dynamo_tpu.serving.frontend import FrontendContext, make_frontend_server


def main(argv=None):
    logging.basicConfig(level=os.environ.get("LOG_LEVEL", "INFO"))
    p = argparse.ArgumentParser(prog="dynamo_tpu.frontend")
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int,
                   default=int(os.environ.get("DYNAMO_PORT", 8000)))
    p.add_argument("--heartbeat-ttl", type=float, default=15.0)
    p.add_argument("--static-workers", default=os.environ.get("STATIC_WORKERS"),
                   help="comma-separated worker URLs (skip heartbeat discovery)")
    p.add_argument("--static-model", default=os.environ.get("STATIC_MODEL"))
    p.add_argument("--etcd-endpoint", default=os.environ.get("ETCD_ENDPOINT"),
                   help="etcd v3 gateway URL; enables cross-replica worker "
                        "registry sync (e.g. http://dynamo-platform-etcd:2379)")
    p.add_argument("--nats-url", default=os.environ.get("NATS_URL"),
                   help="NATS server URL; routes requests to workers over "
                        "the NATS plane (e.g. nats://dynamo-platform-nats:"
                        "4222), with HTTP fallback")
    args = p.parse_args(argv)

    from dynamo_tpu.serving.router import Router

    router = Router(heartbeat_ttl=args.heartbeat_ttl)
    if args.static_workers:
        # static registration never expires
        router.ttl = float("inf")
        for url in args.static_workers.split(","):
            router.register(url.strip(), args.static_model or "?", "agg")
    if args.etcd_endpoint and args.static_workers:
        logging.getLogger("dynamo_tpu.frontend").warning(
            "--static-workers skips discovery entirely; ignoring "
            "--etcd-endpoint (the two modes are mutually exclusive)"
        )
    elif args.etcd_endpoint:
        from dynamo_tpu.serving.registry import EtcdRegistry

        EtcdRegistry(router, args.etcd_endpoint,
                     ttl_s=int(args.heartbeat_ttl)).start()
    ctx = FrontendContext(router, nats_url=args.nats_url)
    srv = make_frontend_server(ctx, args.host, args.port)
    log = logging.getLogger("dynamo_tpu.frontend")

    def drain_then_stop():
        # SIGTERM (rolling restart / scale-down): flip /healthz to 503 so
        # the Service stops sending new streams here, then wait for
        # in-flight requests to finish before stopping the server. Streams
        # cut off by the hard stop are client-resumable through any peer
        # replica (serving/ha.py).
        ctx.draining = True
        budget = float(os.environ.get("FRONTEND_DRAIN_S", "5"))
        deadline = time.monotonic() + budget
        while time.monotonic() < deadline:
            with ctx._inflight_lock:
                n = ctx._inflight
            if n == 0:
                break
            time.sleep(0.2)
        else:
            with ctx._inflight_lock:
                n = ctx._inflight
            if n:
                log.warning("drain budget %.1fs exhausted with %d request(s)"
                            " in flight; stopping anyway", budget, n)
        srv.shutdown()

    def shutdown(*_):
        if ctx.draining:
            # second signal: operator means it — stop immediately
            threading.Thread(target=srv.shutdown, daemon=True).start()
            return
        threading.Thread(target=drain_then_stop, daemon=True).start()

    signal.signal(signal.SIGTERM, shutdown)
    signal.signal(signal.SIGINT, shutdown)
    logging.getLogger("dynamo_tpu.frontend").info(
        "frontend listening on %s:%d", args.host, args.port
    )
    srv.serve_forever()


if __name__ == "__main__":
    main()
