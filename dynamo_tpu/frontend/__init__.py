"""Frontend/router process package (`python -m dynamo_tpu.frontend`)."""
