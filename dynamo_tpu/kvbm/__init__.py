"""KVBM: tiered KV block manager (the Dynamo KVBM analogue).

Three pieces, mirroring the reference platform's block-manager story
(RTP-LLM, arxiv 2605.29639, shows multi-tier KV reuse is the largest TTFT
lever for multi-turn traffic):

- `host_pool`  — a bounded, preallocated host-RAM arena (LRU, pinned-aware)
  that evicted device prefix pages DEMOTE into instead of being destroyed,
  with an optional disk tier behind the same interface;
- `manager`    — the engine-side bridge: `PrefixCache.evict` spills
  sole-owned pages down a tier, `PrefixCache.lookup` misses onboard them
  back (device_put), gated by a roofline-derived restore-vs-recompute
  cost check (`cost_model`), with an optional cross-worker pull over the
  transfer plane;
- `events`     — the cluster-wide KV event plane: workers publish block
  stored/demoted/removed events on NATS; the frontend router builds a
  per-worker global prefix index from them, replacing the guess ledger as
  the primary kv_overlap routing source.
"""

from dynamo_tpu.kvbm.host_pool import DiskBlockTier, HostBlockPool  # noqa: F401
from dynamo_tpu.kvbm.cost_model import OnboardGate  # noqa: F401
from dynamo_tpu.kvbm.manager import KVBM  # noqa: F401
