"""Cluster-wide KV event plane — worker-side publisher.

Workers announce what their KV cache actually holds: `stored` (block on
device), `demoted` (spilled to the host/disk tier, still servable),
`removed` (gone from every tier). The frontend router subscribes
(serving/router.py `KVEventIndex`) and routes follow-up turns to the
worker that REALLY holds the prefix — replacing the frontend's passive
guess ledger as the primary kv_overlap source.

Hash-space bridging: the engine's block hashes chain over TOKEN ids
(engine/kv_cache.py), but the frontend is tokenizer-free — its routing
chain hashes fixed-size TEXT blocks of the canonical prompt
(serving/router.py text_block_chain). The worker sees both: it tokenizes
the same canonical text the frontend hashed, so the publisher records,
per admitted request, the (token-chain, text-chain) pair and translates
engine events into the router's text-hash space by proportional depth
(token page i of P covers text blocks [i*T/P, (i+1)*T/P) of T). Depth is
what routing consumes, so the approximation only blurs WHERE a partial
eviction truncates a prefix, never WHICH worker holds it.

Subject: `dynamo.kv_events.<model-token>.<worker-token>`; the frontend
subscribes to `dynamo.kv_events.>`. Payloads are small JSON batches; the
plane is advisory (at-most-once, like the request plane) — a lost event
degrades routing back to the ledger/HRW path, never correctness.
"""

from __future__ import annotations

import json
import logging
import threading
from typing import Dict, List, Optional, Tuple

log = logging.getLogger("dynamo_tpu.kvbm.events")

SUBJECT_PREFIX = "dynamo.kv_events"


def kv_event_subject(model: str, worker_url: str) -> str:
    from dynamo_tpu.serving.nats import subject_token

    return (f"{SUBJECT_PREFIX}.{subject_token(model)}"
            f".{subject_token(worker_url)}")


def token_block_chain(prompt_token_ids, page_size: int,
                      namespace: str = "") -> List[bytes]:
    """The engine's rolling page-block hash chain for a prompt's FULL
    pages — byte-identical to what PrefixCache.insert publishes (same
    `_chain` AND the same namespace seeding: weight version + LoRA
    adapter), so engine events and publisher groups share keys."""
    from dynamo_tpu.engine.kv_cache import PrefixCache

    n_full = len(prompt_token_ids) // page_size
    out, h = [], (b"root" if not namespace
                  else b"root|" + namespace.encode("utf-8"))
    for i in range(n_full):
        h = PrefixCache._chain(
            h, prompt_token_ids[i * page_size:(i + 1) * page_size])
        out.append(h)
    return out


class _Group:
    """One admitted request's (token-chain, text-chain) association."""

    __slots__ = ("token_hex", "text", "depth")

    def __init__(self, token_hex: List[str], text: List[str]):
        self.token_hex = token_hex
        self.text = text
        self.depth = len(token_hex)  # usable token depth (pages)

    def text_range(self, i: int, j: int) -> List[str]:
        """Text blocks proportionally covered by token pages [i, j)."""
        p = max(len(self.token_hex), 1)
        t = len(self.text)
        return self.text[i * t // p:j * t // p]


class KVEventPublisher:
    """Translates engine KV events into router-space text-hash events and
    publishes them on NATS. Attach with `engine.set_kv_event_sink(pub.on_
    engine_event)`; the serving layer registers each request's canonical
    routing text via `register()` before submission."""

    def __init__(self, nats_client, worker_url: str, model: str,
                 max_groups: int = 4096):
        self.nc = nats_client
        self.worker_url = worker_url
        self.model = model
        self.subject = kv_event_subject(model, worker_url)
        self.max_groups = max_groups
        self._lock = threading.Lock()
        # dict order = LRU over registration
        self._groups: Dict[str, _Group] = {}  # keyed by first token hash
        # token hash hex -> (page index, [group keys]) — shared prefixes
        # hash identically at the same depth, so one hash maps to one index
        self._token_map: Dict[str, Tuple[int, List[str]]] = {}
        self.published_total = 0
        self.publish_errors_total = 0
        self._seq = 0

    # ------------------------------------------------------------ register --
    def register(self, prompt_token_ids, routing_text: str,
                 page_size: int, namespace: str = "") -> None:
        """Record one request's token-chain <-> text-chain association.
        `routing_text` must be the same canonical text the frontend hashed
        (completions: the prompt string; chat: json.dumps(messages));
        `namespace` the engine's active KV namespace (weight version) so
        the token chain keys match what the engine will publish."""
        from dynamo_tpu.serving.router import text_block_chain

        tokens_hex = [h.hex()
                      for h in token_block_chain(prompt_token_ids, page_size,
                                                 namespace)]
        if not tokens_hex:
            return
        text = text_block_chain(routing_text)
        if not text:
            return
        key = tokens_hex[0] + f":{len(tokens_hex)}"
        g = _Group(tokens_hex, text)
        with self._lock:
            if key in self._groups:
                self._groups[key] = self._groups.pop(key)  # LRU bump
                return
            self._groups[key] = g
            for i, th in enumerate(tokens_hex):
                idx, keys = self._token_map.setdefault(th, (i, []))
                keys.append(key)
            while len(self._groups) > self.max_groups:
                old_key, old = next(iter(self._groups.items()))
                del self._groups[old_key]
                for th in old.token_hex:
                    ent = self._token_map.get(th)
                    if ent is None:
                        continue
                    if old_key in ent[1]:
                        ent[1].remove(old_key)
                    if not ent[1]:
                        del self._token_map[th]

    # -------------------------------------------------------------- events --
    def on_engine_event(self, kind: str, block_hashes: List[bytes],
                        tier: str) -> None:
        """Engine sink: translate token-hash events to text-hash events."""
        text_blocks: List[str] = []
        seen = set()
        with self._lock:
            for h in block_hashes:
                ent = self._token_map.get(h.hex())
                if ent is None:
                    continue
                i, keys = ent
                for key in keys:
                    g = self._groups.get(key)
                    if g is None:
                        continue
                    if kind == "removed":
                        # a prefix chain is only usable up to its first
                        # missing page: truncate the group there
                        if i < g.depth:
                            covered = g.text_range(i, len(g.token_hex))
                            g.depth = i
                        else:
                            covered = []
                    else:
                        covered = g.text_range(i, i + 1)
                        if kind == "stored" and i >= g.depth:
                            g.depth = i + 1
                    for t in covered:
                        if t not in seen:
                            seen.add(t)
                            text_blocks.append(t)
        if text_blocks:
            self.publish(kind, text_blocks, tier)

    def publish(self, kind: str, text_blocks: List[str], tier: str) -> None:
        with self._lock:
            self._seq += 1
            seq = self._seq
        payload = {
            "v": 1,
            "type": kind,
            "worker": self.worker_url,
            "model": self.model,
            "blocks": text_blocks,
            "tier": tier,
            "seq": seq,
        }
        try:
            self.nc.publish(self.subject, json.dumps(payload).encode())
            with self._lock:
                self.published_total += 1
        except Exception as e:  # plane down -> routing degrades, not serving
            with self._lock:
                self.publish_errors_total += 1
            log.debug("kv event publish failed: %s", e)

    def stats(self) -> dict:
        with self._lock:
            return {
                "groups": len(self._groups),
                "published_total": self.published_total,
                "publish_errors_total": self.publish_errors_total,
            }
