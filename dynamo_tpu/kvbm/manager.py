"""KVBM manager: the engine-side bridge between the device prefix cache and
the lower tiers (host RAM, disk, peer workers).

Wiring: `Engine` constructs a KVBM when `kvbm_host_blocks > 0` and attaches
it to its `PrefixCache`. From then on:

- `PrefixCache.evict` DEMOTES sole-owned victim pages through `demote()`
  (one batched device gather -> arena memcpy) instead of destroying them;
  pages the pool can't take fall back to a plain free.
- `PrefixCache.lookup` misses consult `onboard_chain()`: consecutive
  blocks found in the host tier (or a peer's, via the transfer plane) are
  restored with one padded scatter (`jax.device_put` + the engine's jitted
  page import), gated by the roofline restore-vs-recompute check.

Every device call here runs under the engine's `_exec_lock` — demote and
onboard only fire from `evict()`/`lookup()`, whose callers (admission,
page growth, KV import) all hold it.

Threading note: the `events` sink (kvbm/events.py) and the metrics
counters are touched from the scheduler thread; the host pool itself is
lock-protected because peer-serving threads read it concurrently.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, List, Optional, Tuple

import numpy as np

from dynamo_tpu.kvbm.cost_model import OnboardGate
from dynamo_tpu.kvbm.host_pool import DiskBlockTier, HostBlockPool

log = logging.getLogger("dynamo_tpu.kvbm")


def _pad_pow2(n: int) -> int:
    """Pad batched page gathers/scatters to a power of two so the eager
    gather and the jitted import compile O(log) distinct shapes, not one
    per prefix length."""
    b = 1
    while b < n:
        b *= 2
    return b


class KVBM:
    """Tiered KV block manager for one engine."""

    def __init__(self, engine, cfg=None):
        cfg = cfg or engine.cfg
        self.engine = engine
        spec = engine.kv_spec
        import jax.numpy as jnp

        self.block_shape = (spec.num_layers, spec.page_size, spec.lane_width)
        self._np_dtype = np.dtype(jnp.dtype(spec.dtype))
        disk = None
        if getattr(cfg, "kvbm_disk_dir", None):
            disk = DiskBlockTier(cfg.kvbm_disk_dir,
                                 capacity_blocks=cfg.kvbm_disk_blocks)
        self.pool = HostBlockPool(cfg.kvbm_host_blocks, self.block_shape,
                                  self._np_dtype, disk=disk)
        self.gate = OnboardGate(
            mode=getattr(cfg, "kvbm_gate", "auto"),
            model_cfg=engine.model_cfg,
            block_nbytes=self.pool.block_nbytes,
            page_size=cfg.page_size,
            prefill_chunk_tokens=cfg.prefill_chunk_tokens or cfg.page_size,
        )
        # cluster plane hooks (set by the serving layer):
        # events(kind, [hash bytes], tier) -> None; kinds: stored | demoted
        # | removed. peer_fetch([hash bytes]) -> [(k, v)] consecutive-from-
        # the-start host-layout blocks pulled from a peer's host tier.
        self.events: Optional[Callable[[str, List[bytes], str], None]] = None
        self.peer_fetch: Optional[
            Callable[[List[bytes]], List[Tuple[np.ndarray, np.ndarray]]]
        ] = None
        self.tracer = None  # set by ServingContext; spans kvbm.offload/onboard
        # integrity sentinel (DYNAMO_TPU_INTEGRITY=full; docs/robustness.md
        # "Engine watchdog & quarantine"): CRC32 per demoted block, verified
        # at onboard — a mismatch (host-RAM/disk bit flip) drops the block
        # to a cache miss (recompute) instead of importing silent corruption
        # into the device pool. Peer-fetched blocks carry no local CRC and
        # skip verification.
        from dynamo_tpu.robustness.watchdog import integrity_mode

        self._checksum = integrity_mode() == "full"
        self._crc: dict = {}  # block hash -> crc32 at demote time
        self._lock = threading.Lock()  # counters only
        # counters behind the dynamo_kvbm_* metric series
        self.host_hits_total = 0        # lookups served >= 1 block from tiers
        self.host_hit_blocks_total = 0
        self.host_misses_total = 0      # lookup tails the tiers couldn't serve
        self.demoted_blocks_total = 0
        self.onboarded_blocks_total = 0
        self.peer_onboarded_blocks_total = 0
        self.removed_blocks_total = 0
        self.gate_recompute_total = 0   # onboards the cost gate refused

    # ------------------------------------------------------------- helpers --
    def _emit(self, kind: str, hashes: List[bytes], tier: str) -> None:
        if self.events is None or not hashes:
            return
        try:
            self.events(kind, list(hashes), tier)
        except Exception:  # the event plane must never break serving
            log.exception("kvbm event sink failed")

    def _span(self, name: str, **attrs):
        if self.tracer is None:
            from dynamo_tpu.observability import tracing as obs_tracing

            return obs_tracing.NOOP_SPAN
        return self.tracer.start_span(name, attributes=attrs)

    def _flight(self, event: str, **fields):
        """Tier moves land in the engine's flight ring: the KVBM runs on
        the engine thread (evict/onboard inside admission), so the note
        attaches to the very step record whose admission caused the move."""
        flight = getattr(self.engine, "flight", None)
        if flight is not None:
            flight.note(event, **fields)

    # -------------------------------------------------------------- demote --
    def demote(self, victims: List[Tuple[bytes, int]]) -> int:
        """Spill evicted sole-owned pages into the host tier. One padded
        device gather covers the whole victim batch; pages the pool cannot
        take (full-of-pinned, arena rejected) are reported `removed` and
        the caller frees them as before. Returns blocks demoted."""
        if not victims:
            return 0
        span = self._span("kvbm.offload", blocks=len(victims))
        try:
            import jax.numpy as jnp

            eng = self.engine
            pages = [p for _, p in victims]
            width = _pad_pow2(len(pages))
            idx = np.zeros((width,), np.int32)  # pad rows gather trash page 0
            idx[:len(pages)] = pages
            k = np.asarray(jnp.take(eng.k_pages, jnp.asarray(idx), axis=1))
            v = np.asarray(jnp.take(eng.v_pages, jnp.asarray(idx), axis=1))
            demoted, removed, dropped = [], [], []
            for i, (h, _) in enumerate(victims):
                ok, lru_removed = self.pool.put(h, k[:, i], v[:, i])
                dropped.extend(lru_removed)
                (demoted if ok else removed).append(h)
                if self._checksum and ok:
                    import zlib

                    self._crc[h] = zlib.crc32(
                        v[:, i].tobytes(),
                        zlib.crc32(k[:, i].tobytes()))
            if self._checksum:
                for h in removed + dropped:
                    self._crc.pop(h, None)
            with self._lock:
                self.demoted_blocks_total += len(demoted)
                self.removed_blocks_total += len(removed) + len(dropped)
            self._emit("demoted", demoted, "host")
            self._emit("removed", removed + dropped, "none")
            span.set_attributes({"demoted": len(demoted),
                                 "removed": len(removed) + len(dropped)})
            self._flight("kvbm_demote", blocks=len(demoted),
                         removed=len(removed) + len(dropped))
            return len(demoted)
        except Exception:
            log.exception("kvbm demote failed; pages freed undemoted")
            span.set_status("ERROR", "demote failed")
            return 0
        finally:
            span.end()

    def demote_all(self, prefix_cache) -> int:
        """Graceful-drain handoff: spill EVERY sole-owned prefix page into
        the host tier (prefix_cache.evict routes victims through demote()
        above, which publishes `demoted` events). Surviving workers keep
        routing on those blocks via the KV event index and onboard them
        over the cross-worker host-tier fetch — the departing worker's
        warm prefixes outlive the pod. Caller holds the engine exec lock.
        Returns pages demoted/evicted."""
        return prefix_cache.evict(prefix_cache.evictable())

    def _verify(self, h: bytes, k: np.ndarray, v: np.ndarray) -> bool:
        """Onboard-time CRC check (integrity=full). A mismatch means the
        block rotted in host RAM or on disk since demote: drop it from
        every tier (a cache miss — the prefix recomputes, correctly),
        count the fault on the watchdog, and never abort anything — the
        corruption was caught BEFORE it touched the device pool."""
        import zlib

        want = self._crc.get(h)
        if want is None:
            return True  # peer-fetched or pre-sentinel block: no claim
        got = zlib.crc32(v.tobytes(), zlib.crc32(k.tobytes()))
        if got == want:
            return True
        self._crc.pop(h, None)
        self.pool.drop(h)
        with self._lock:
            self.removed_blocks_total += 1
        self._emit("removed", [h], "none")
        self._flight("integrity_fault", sentinel="kv_checksum",
                     block=h.hex()[:16])
        wd = getattr(self.engine, "watchdog", None)
        if wd is not None:
            wd.record_integrity_fault("kv_checksum", [],
                                      block=h.hex()[:16])
        log.warning("kvbm checksum mismatch on block %s; dropped "
                    "(recompute)", h.hex()[:16])
        return False

    # ------------------------------------------------------------- onboard --
    def onboard_chain(self, hashes: List[bytes]) -> List[Tuple[bytes, int]]:
        """Restore the longest consecutive run of `hashes` available in the
        lower tiers back into the device pool. Returns [(hash, page_id)]
        with each new page holding ONE allocator ref (cache-owned, exactly
        like a freshly inserted prefix page); the caller republishes them
        in its hash map. Gated by the restore-vs-recompute check."""
        if not hashes:
            return []
        disk_drops: List[bytes] = []
        blocks: List[Tuple[bytes, np.ndarray, np.ndarray]] = []
        for h in hashes:
            got = self.pool.get(h, removed=disk_drops)
            if got is None:
                break
            if self._checksum and not self._verify(h, got[0], got[1]):
                break  # the chain must stay consecutive: stop before it
            blocks.append((h, got[0], got[1]))
        source = "host"
        if not blocks and self.peer_fetch is not None:
            blocks = self._fetch_from_peer(hashes)
            source = "peer"
        if disk_drops:
            for h in disk_drops:
                self._crc.pop(h, None)
            with self._lock:
                self.removed_blocks_total += len(disk_drops)
            self._emit("removed", disk_drops, "none")
        if not blocks:
            with self._lock:
                self.host_misses_total += 1
            return []
        eng = self.engine
        # cost gate FIRST — a refused onboard must not have demoted other
        # prefixes to make room for nothing
        if not self.gate.should_onboard(len(blocks)):
            with self._lock:
                self.gate_recompute_total += self.gate.skipped
                self.gate.skipped = 0
                self.host_misses_total += 1
            self._flight("kvbm_gate_recompute", blocks=len(blocks),
                         source=source)
            return []
        # make device room by rotating OTHER sole-owned cache entries down
        # a tier (they demote, not die — the incoming prefix is the hot
        # one); the chain's own hashes are protected from eviction, and
        # whatever room can't be made truncates the onboard
        free = eng.allocator.free_pages
        if len(blocks) > free and eng.prefix_cache is not None:
            eng.prefix_cache.evict(len(blocks) - free,
                                   protect=frozenset(hashes))
            free = eng.allocator.free_pages
        if len(blocks) > free:
            blocks = blocks[:free]
        if not blocks:
            with self._lock:
                self.host_misses_total += 1
            return []
        span = self._span("kvbm.onboard", blocks=len(blocks), source=source)
        try:
            import jax.numpy as jnp

            pages = eng.allocator.alloc(len(blocks))
            width = _pad_pow2(len(blocks))
            idx = np.zeros((width,), np.int32)  # pad rows scatter onto trash
            idx[:len(pages)] = pages
            k_new = np.zeros((self.block_shape[0], width) + self.block_shape[1:],
                             self._np_dtype)
            v_new = np.zeros_like(k_new)
            for i, (_, kb, vb) in enumerate(blocks):
                k_new[:, i] = kb
                v_new[:, i] = vb
            eng.k_pages, eng.v_pages = eng._import(
                eng.k_pages, eng.v_pages, jnp.asarray(idx),
                jnp.asarray(k_new), jnp.asarray(v_new),
            )
            out = [(h, p) for (h, _, _), p in zip(blocks, pages)]
            with self._lock:
                self.host_hits_total += 1
                self.host_hit_blocks_total += len(out)
                self.onboarded_blocks_total += len(out)
                if source == "peer":
                    self.peer_onboarded_blocks_total += len(out)
            self._emit("stored", [h for h, _ in out], "device")
            span.set_attribute("onboarded", len(out))
            self._flight("kvbm_onboard", blocks=len(out), source=source)
            return out
        except Exception:
            log.exception("kvbm onboard failed; falling back to recompute")
            span.set_status("ERROR", "onboard failed")
            return []
        finally:
            span.end()

    def _fetch_from_peer(self, hashes: List[bytes]
                         ) -> List[Tuple[bytes, np.ndarray, np.ndarray]]:
        """Cross-worker onboard: pull the prefix blocks from a peer's host
        tier over the transfer plane instead of re-prefilling. Fetch
        failures mean recompute, never a request failure."""
        try:
            got = self.peer_fetch(hashes)
        except Exception as e:
            log.warning("kvbm peer fetch failed (%s); recomputing", e)
            return []
        out = []
        for h, (kb, vb) in zip(hashes, got):
            if kb.shape != self.block_shape or kb.dtype != self._np_dtype:
                log.warning("kvbm peer block layout mismatch "
                            "(%s/%s vs %s/%s); recomputing",
                            kb.shape, kb.dtype, self.block_shape,
                            self._np_dtype)
                return []
            out.append((h, kb, vb))
        return out

    # --------------------------------------------------------------- stats --
    def notify_stored(self, hashes: List[bytes]) -> None:
        """PrefixCache.insert hook: freshly published device blocks."""
        self._emit("stored", hashes, "device")

    def stats(self) -> dict:
        with self._lock:
            out = {
                "host_hits_total": self.host_hits_total,
                "host_hit_blocks_total": self.host_hit_blocks_total,
                "host_misses_total": self.host_misses_total,
                "demoted_blocks_total": self.demoted_blocks_total,
                "onboarded_blocks_total": self.onboarded_blocks_total,
                "peer_onboarded_blocks_total": self.peer_onboarded_blocks_total,
                "removed_blocks_total": self.removed_blocks_total,
                "gate_recompute_total": self.gate_recompute_total,
            }
        out["host_pool"] = self.pool.stats()
        out["gate"] = self.gate.explain(1)
        return out
