"""Bounded host-RAM block pool (+ optional disk tier) for demoted KV pages.

One block = one KV page's K and V slabs ([num_layers, page_size, lane_width]
each, the exact device-page layout of engine/kv_cache.py), keyed by the
PrefixCache's rolling block-hash digest so a demoted page round-trips back
onto the device bit-exactly for any KV dtype (bf16, fp32, packed int8 rows).

The arena is PREALLOCATED at construction — the steady-state demote path
only memcpys into it, never allocates, so host-RAM footprint is a config
knob (`kvbm_host_blocks * block_nbytes`), not a traffic function. Eviction
is LRU over unpinned entries; `pin`/`unpin` protect a block while a peer
worker streams it over the transfer plane (an LRU eviction mid-serve would
hand the peer another block's bytes).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

log = logging.getLogger("dynamo_tpu.kvbm")


class DiskBlockTier:
    """Disk tier behind the host pool: blocks LRU-evicted from host RAM
    spill here (bounded by `capacity_blocks`); host-pool misses check it
    before giving up. One file per block: K bytes then V bytes, raw
    C-order — the shape/dtype contract lives in the owning pool."""

    def __init__(self, directory: str, capacity_blocks: int = 256):
        self.dir = directory
        self.capacity = capacity_blocks
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._lru: Dict[bytes, str] = {}  # guarded_by: _lock — hash -> path, insertion order = LRU
        self.stored = 0
        self.hits = 0
        self.dropped = 0

    def _path(self, block_hash: bytes) -> str:
        return os.path.join(self.dir, block_hash.hex() + ".kv")

    def put(self, block_hash: bytes, k: np.ndarray, v: np.ndarray
            ) -> List[bytes]:
        """Store one block; returns the hashes DROPPED to make room."""
        dropped: List[bytes] = []
        path = self._path(block_hash)
        with self._lock:
            if block_hash in self._lru:
                self._lru[block_hash] = self._lru.pop(block_hash)
                return dropped
            while len(self._lru) >= self.capacity:
                old, old_path = next(iter(self._lru.items()))
                del self._lru[old]
                try:
                    os.remove(old_path)
                except OSError:
                    pass
                dropped.append(old)
                self.dropped += 1
        # the slow disk write runs with the lock RELEASED so concurrent
        # get()/put() on other blocks never stall behind it; the file is
        # content-addressed, so racing writers of the same hash produce
        # identical bytes and the capacity bound is soft by at most the
        # width of the race
        try:
            with open(path, "wb") as f:
                f.write(np.ascontiguousarray(k).view(np.uint8).tobytes())
                f.write(np.ascontiguousarray(v).view(np.uint8).tobytes())
        except OSError as e:
            log.warning("disk tier write failed for %s: %s",
                        block_hash.hex()[:12], e)
            return dropped
        with self._lock:
            if block_hash not in self._lru:
                self._lru[block_hash] = path
                self.stored += 1
        return dropped

    def get(self, block_hash: bytes, shape, dtype
            ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        with self._lock:
            path = self._lru.get(block_hash)
            if path is None:
                return None
            self._lru[block_hash] = self._lru.pop(block_hash)  # LRU bump
        try:
            raw = open(path, "rb").read()
        except OSError:
            with self._lock:
                self._lru.pop(block_hash, None)
            return None
        half = len(raw) // 2
        k = np.frombuffer(raw[:half], dtype=np.uint8).view(dtype).reshape(shape)
        v = np.frombuffer(raw[half:], dtype=np.uint8).view(dtype).reshape(shape)
        self.hits += 1
        return k.copy(), v.copy()

    def contains(self, block_hash: bytes) -> bool:
        with self._lock:
            return block_hash in self._lru

    def __len__(self) -> int:
        with self._lock:
            return len(self._lru)


class HostBlockPool:
    """Preallocated host-RAM KV block arena with LRU eviction and pinning."""

    def __init__(self, capacity_blocks: int, block_shape, dtype,
                 disk: Optional[DiskBlockTier] = None):
        if capacity_blocks <= 0:
            raise ValueError("capacity_blocks must be > 0")
        self.capacity = capacity_blocks
        self.block_shape = tuple(block_shape)
        self.dtype = np.dtype(dtype)
        # [capacity, 2(K/V)] + block_shape — one contiguous slab, allocated
        # once; a block's K is arena[slot, 0], V is arena[slot, 1]
        self._arena = np.empty((capacity_blocks, 2) + self.block_shape,
                               self.dtype)
        self._free: List[int] = list(range(capacity_blocks - 1, -1, -1))  # guarded_by: _lock
        self._entries: Dict[bytes, int] = {}  # guarded_by: _lock — hash -> slot, dict order = LRU
        self._pins: Dict[bytes, int] = {}  # guarded_by: _lock
        self._lock = threading.Lock()
        self.disk = disk
        # counters (exposed as dynamo_kvbm_* series by the serving layer)
        self.hits = 0
        self.misses = 0
        self.stored = 0
        self.evicted_lru = 0
        self.rejected_full = 0

    @property
    def block_nbytes(self) -> int:
        return 2 * int(np.prod(self.block_shape)) * self.dtype.itemsize

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # ---------------------------------------------------------------- write --
    def put(self, block_hash: bytes, k: np.ndarray, v: np.ndarray
            ) -> Tuple[bool, List[bytes]]:
        """Store one block (copy into the arena). Returns (stored, removed):
        `removed` lists hashes dropped from EVERY tier to make room (the
        event plane publishes them as gone). A full pool whose entries are
        all pinned rejects the put — the caller falls back to a plain free."""
        removed: List[bytes] = []
        with self._lock:
            if block_hash in self._entries:
                self._entries[block_hash] = self._entries.pop(block_hash)
                return True, removed
            slot = self._alloc_slot_locked(removed)
            if slot is None:
                self.rejected_full += 1
                return False, removed
            np.copyto(self._arena[slot, 0], k, casting="no")
            np.copyto(self._arena[slot, 1], v, casting="no")
            self._entries[block_hash] = slot
            self.stored += 1
        return True, removed

    def _alloc_slot_locked(self, removed: List[bytes]) -> Optional[int]:  # holds: _lock
        if self._free:
            return self._free.pop()
        # LRU-evict the oldest unpinned entry; spill it to disk if a tier
        # is configured (then only disk's own overflow is truly removed)
        for old, slot in self._entries.items():
            if self._pins.get(old, 0) > 0:
                continue
            del self._entries[old]
            self.evicted_lru += 1
            if self.disk is not None:
                removed.extend(self.disk.put(
                    old, self._arena[slot, 0], self._arena[slot, 1]))
            else:
                removed.append(old)
            return slot
        return None  # everything pinned

    # ----------------------------------------------------------------- read --
    def get(self, block_hash: bytes, removed: Optional[List[bytes]] = None
            ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Copy a block out (host RAM first, then the disk tier — a disk hit
        re-onboards into host RAM). None on miss. `removed`, when given,
        collects hashes a disk-promotion displaced out of every tier (the
        caller owes the event plane a `removed` for them)."""
        with self._lock:
            slot = self._entries.get(block_hash)
            if slot is not None:
                self._entries[block_hash] = self._entries.pop(block_hash)
                self.hits += 1
                return self._arena[slot, 0].copy(), self._arena[slot, 1].copy()
        if self.disk is not None:
            got = self.disk.get(block_hash, self.block_shape, self.dtype)
            if got is not None:
                self.hits += 1
                _, dropped = self.put(block_hash, got[0], got[1])  # re-promote
                if removed is not None:
                    removed.extend(dropped)
                return got
        with self._lock:
            self.misses += 1
        return None

    def contains(self, block_hash: bytes) -> bool:
        with self._lock:
            if block_hash in self._entries:
                return True
        return self.disk is not None and self.disk.contains(block_hash)

    # ------------------------------------------------------------ lifecycle --
    def pin(self, block_hash: bytes) -> bool:
        with self._lock:
            if block_hash not in self._entries:
                return False
            self._pins[block_hash] = self._pins.get(block_hash, 0) + 1
            return True

    def unpin(self, block_hash: bytes) -> None:
        with self._lock:
            n = self._pins.get(block_hash, 0) - 1
            if n <= 0:
                self._pins.pop(block_hash, None)
            else:
                self._pins[block_hash] = n

    def drop(self, block_hash: bytes) -> bool:
        with self._lock:
            slot = self._entries.pop(block_hash, None)
            if slot is None:
                return False
            self._free.append(slot)
            self._pins.pop(block_hash, None)
            return True

    def stats(self) -> dict:
        with self._lock:
            out = {
                "capacity_blocks": self.capacity,
                "used_blocks": len(self._entries),
                "block_nbytes": self.block_nbytes,
                "hits": self.hits,
                "misses": self.misses,
                "stored": self.stored,
                "evicted_lru": self.evicted_lru,
                "rejected_full": self.rejected_full,
            }
        if self.disk is not None:
            out["disk"] = {
                "used_blocks": len(self.disk),
                "capacity_blocks": self.disk.capacity,
                "hits": self.disk.hits,
                "dropped": self.disk.dropped,
            }
        return out
