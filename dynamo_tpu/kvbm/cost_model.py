"""Restore-vs-recompute gate for KV onboarding.

A host-tier hit is only worth taking when restoring the pages
(host->device DMA + one scatter dispatch) beats recomputing them (a
chunked-prefill pass over the same tokens). Both sides come from the
serving roofline (`profiler/roofline.py`): recompute is compute-bound
prefill FLOPs plus a dispatch overhead per chunk; restore is bytes over
the host<->device link plus one dispatch. On real models restore wins by
an order of magnitude — the reason KV offload exists — but the gate keeps
degenerate cases (tiny prompts on fast chips, a crawling disk tier)
honest instead of hard-coding "always onboard".
"""

from __future__ import annotations

import os
from typing import Optional

from dynamo_tpu.profiler import roofline

# host<->device staging bandwidth (bytes/s). TPU hosts stream HBM over
# PCIe-class links; 8 GB/s is the conservative planning number, overridable
# per deployment (DYNAMO_TPU_KVBM_H2D_GBPS).
DEFAULT_H2D_BYTES_S = 8e9
# fixed cost of one host->device scatter dispatch / one prefill-chunk
# dispatch (same constant family as roofline.DISPATCH_OVERHEAD_S)
TRANSFER_OVERHEAD_S = 0.0005


def _h2d_bytes_s() -> float:
    try:
        return float(os.environ.get("DYNAMO_TPU_KVBM_H2D_GBPS", "0")) * 1e9 \
            or DEFAULT_H2D_BYTES_S
    except ValueError:
        return DEFAULT_H2D_BYTES_S


class OnboardGate:
    """Decides whether to restore N cached blocks or recompute them.

    mode: "auto" (roofline compare) | "always" | "never". `chip_flops`
    defaults to the detected chip's peak when the engine runs on TPU and
    to the v5e planning number elsewhere (CPU tests/dev boxes — where the
    real recompute is far SLOWER than the model assumes, so auto remains
    conservative in the onboard direction)."""

    def __init__(self, mode: str = "auto", model_cfg=None,
                 block_nbytes: int = 0, page_size: int = 16,
                 prefill_chunk_tokens: int = 256,
                 chip_flops: Optional[float] = None,
                 bytes_per_s: Optional[float] = None):
        if mode not in ("auto", "always", "never"):
            raise ValueError(f"kvbm_gate must be auto|always|never, "
                             f"got {mode!r}")
        self.mode = mode
        self.model_cfg = model_cfg
        self.block_nbytes = block_nbytes
        self.page_size = page_size
        self.chunk_tokens = max(prefill_chunk_tokens, page_size)
        self.chip_flops = chip_flops or _detect_chip_flops()
        self.bytes_per_s = bytes_per_s or _h2d_bytes_s()
        self.skipped = 0  # onboards refused (recompute was cheaper)

    def restore_seconds(self, n_blocks: int) -> float:
        return roofline.kvbm_restore_seconds(
            n_blocks * self.block_nbytes, self.bytes_per_s,
            overhead_s=TRANSFER_OVERHEAD_S)

    def recompute_seconds(self, n_blocks: int) -> float:
        n_tokens = n_blocks * self.page_size
        n_chunks = max(1, -(-n_tokens // self.chunk_tokens))
        return roofline.kvbm_recompute_seconds(
            self.model_cfg, n_tokens, self.chip_flops, n_dispatches=n_chunks)

    def should_onboard(self, n_blocks: int) -> bool:
        if n_blocks <= 0 or self.mode == "never":
            if self.mode == "never" and n_blocks > 0:
                self.skipped += 1
            return False
        if self.mode == "always" or self.model_cfg is None:
            return True
        ok = self.restore_seconds(n_blocks) <= self.recompute_seconds(n_blocks)
        if not ok:
            self.skipped += 1
        return ok

    def explain(self, n_blocks: int) -> dict:
        return {
            "n_blocks": n_blocks,
            "restore_s": round(self.restore_seconds(n_blocks), 6),
            "recompute_s": round(self.recompute_seconds(max(n_blocks, 1)), 6)
            if self.model_cfg is not None else None,
            "mode": self.mode,
        }


def _detect_chip_flops() -> float:
    """Peak bf16 FLOPs of the chip actually serving, for the recompute
    side of the gate; the v5e planning number when detection fails (CPU
    tests, unknown chips)."""
    try:
        import jax

        from dynamo_tpu.profiler.systems import CHIPS

        kind = (getattr(jax.devices()[0], "device_kind", "") or "").lower()
        import re

        for pat, name in [(r"v5 ?lite|v5e", "v5e"), (r"v5p|v5 ?pod", "v5p"),
                          (r"v6e|v6 ?lite|trillium", "v6e"), (r"v4", "v4")]:
            if re.search(pat, kind):
                return CHIPS[name].bf16_flops
    except Exception:
        pass
    try:
        from dynamo_tpu.profiler.systems import CHIPS

        return CHIPS["v5e"].bf16_flops
    except Exception:
        return 2e14
