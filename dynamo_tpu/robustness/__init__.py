"""Failure-domain hardening for the serving plane (ISSUE 2).

Three cooperating pieces, each stdlib-only and individually importable:

- `faults`   — deterministic fault-injection plane: named fault points
  compiled into the serving path (`http_base`, `frontend`, `disagg`,
  `nats`, `engine_service`), armed via env/HTTP, seeded so chaos tests
  replay byte-identically (docs/robustness.md).
- `breaker`  — per-worker circuit breakers with half-open probes; the
  Router consults them on every pick and the frontend exports their
  state at /metrics.
- `deadline` — end-to-end deadline propagation: the client budget rides
  an `x-deadline` header frontend -> worker -> prefill RPC, each hop
  subtracting its own elapsed time; an exhausted budget sheds load with
  504 + Retry-After instead of holding an engine slot.
"""
