"""Engine watchdog & device-fault quarantine.

Every robustness plane so far (journaled recovery, drain handoff, burn-
gated rollouts) assumes the engine itself stays sane.  It does not: a
hung device dispatch wedges ``step()`` under ``_exec_lock`` forever, and
a silently-corrupted forward (NaN logits, bad chip) streams garbage with
a 200 status.  This module closes that failure domain with one
invariant: *the engine is either provably making progress or provably
out of rotation*.

Three cooperating pieces:

1. **Hung-dispatch watchdog.**  The stepline already brackets every
   device seam with ``dispatch``/``device_wait`` phases; the timeline
   mirrors those seams into :meth:`EngineWatchdog.device_enter` /
   :meth:`device_exit`.  A lazy monitor thread checks the armed seam
   against a deadline (``DYNAMO_TPU_STEP_DEADLINE_S`` override, else a
   warmup-measured seam-time EWMA x margin with a floor).  A blown
   deadline *trips* the watchdog: the worker goes ``suspect``, serving
   sheds ``/v1/*`` with 503, the flight recorder dumps the open draft,
   and the escalation ladder fires.

2. **Health state machine.** ::

       healthy -> suspect -> resurrecting -> healthy
                     |
                     +--> quarantined        (terminal)

   The escalation ladder resurrects a suspect engine in place (fresh KV
   pool, re-``device_put`` weights through the elasticity staging path,
   re-warmup) once the wedged dispatch returns control; journaled
   streams hand off through the drain-handoff plane meanwhile and
   resume byte-identically on a peer.  Repeated trips within
   ``DYNAMO_TPU_QUARANTINE_WINDOW_S`` mean the device is not coming
   back: the worker is quarantined permanently, readiness goes 503, the
   operator replaces the pod and planner capacity excludes it.

3. **Integrity sentinels** (``DYNAMO_TPU_INTEGRITY=off|logits|full``).
   A finite-check on prefill logits rides the existing first-token
   readback (no extra device sync) and a host-side sanity check covers
   decode-window readbacks; ``full`` adds KV-page checksums at the KVBM
   demote/onboard boundary.  A tripped sentinel aborts ONLY the
   poisoned streams with a typed ``integrity_fault`` flight event —
   never the process, and never the health state machine (corruption is
   per-batch; hangs are per-device).

Trip handling runs on the monitor thread and deliberately never touches
``_exec_lock`` — the whole point is that the scheduler thread may be
wedged under it.  Resurrection runs on a separate escalation thread
that *does* block on the lock: a simulated hang eventually returns and
resurrection proceeds; a real hang never returns, which leaves the
worker suspect and shedding until the operator replaces the pod —
exactly the "provably out of rotation" half of the invariant.

Env knobs (registered in dynalint KNOWN_ENV):

- ``DYNAMO_TPU_STEP_DEADLINE_S`` — hard per-seam deadline override;
  unset derives ``max(floor, ewma * margin)`` from observed seam times.
  The derived deadline only arms on real accelerators
  (``derive_deadline``): on the CPU fallback a mid-seam XLA recompile
  routinely dwarfs any measured EWMA (there is no AOT warmup guarantee
  off-TPU), so without an explicit override the monitor observes but
  never trips there — CI drills set the override;
- ``DYNAMO_TPU_QUARANTINE_WINDOW_S`` (default 300) — two trips inside
  this window quarantine the worker permanently;
- ``DYNAMO_TPU_INTEGRITY`` (default ``logits``) — sentinel tier.
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Callable, Deque, Dict, List, Optional

log = logging.getLogger("dynamo_tpu.watchdog")

DEADLINE_ENV = "DYNAMO_TPU_STEP_DEADLINE_S"
QUARANTINE_WINDOW_ENV = "DYNAMO_TPU_QUARANTINE_WINDOW_S"
INTEGRITY_ENV = "DYNAMO_TPU_INTEGRITY"

DEFAULT_QUARANTINE_WINDOW_S = 300.0
# without an EWMA yet (pre-warmup) or an env override, never trip a seam
# faster than this — cold dispatches legitimately include compilation
DEFAULT_DEADLINE_FLOOR_S = 2.0
# EWMA multiplier: decode seams are milliseconds, so even 20x stays far
# below human-visible; a genuine hang overshoots by orders of magnitude
DEFAULT_DEADLINE_MARGIN = 20.0
EWMA_ALPHA = 0.2
# monitor thread parks itself after this long with no armed seam: the
# thread pins watchdog -> engine (params, KV pool) via its bound-method
# target, so an idle monitor would keep a retired engine immortal.
# device_enter restarts it on the next dispatch.
MONITOR_IDLE_EXIT_S = 5.0

# /metrics encoding of health (docs/robustness.md)
HEALTH_CODES = {"healthy": 0, "suspect": 1, "resurrecting": 2,
                "quarantined": 3}

INTEGRITY_MODES = ("off", "logits", "full")


def integrity_mode() -> str:
    """Resolved ``DYNAMO_TPU_INTEGRITY`` tier; unknown values fall back
    to the default ``logits`` (cheap, always worth it)."""
    raw = os.environ.get(INTEGRITY_ENV, "logits").strip().lower()
    return raw if raw in INTEGRITY_MODES else "logits"


def _env_deadline() -> Optional[float]:
    raw = os.environ.get(DEADLINE_ENV, "").strip()
    if not raw:
        return None
    try:
        v = float(raw)
        return v if v > 0 else None
    except ValueError:
        log.warning("bad %s=%r; deriving deadline from EWMA", DEADLINE_ENV,
                    raw)
        return None


def _env_quarantine_window() -> float:
    raw = os.environ.get(QUARANTINE_WINDOW_ENV, "").strip()
    if not raw:
        return DEFAULT_QUARANTINE_WINDOW_S
    try:
        return max(0.0, float(raw))
    except ValueError:
        return DEFAULT_QUARANTINE_WINDOW_S


class IntegrityFault(RuntimeError):
    """A sentinel caught device-side corruption (non-finite logits,
    out-of-range token, KV checksum mismatch).  Carries the poisoned
    request ids so callers abort exactly those streams and nothing
    else."""

    def __init__(self, sentinel: str, rids: List[str], detail: str = ""):
        self.sentinel = sentinel
        self.rids = list(rids)
        super().__init__(
            f"integrity fault [{sentinel}] rids={self.rids} {detail}".strip())


class EngineWatchdog:
    """Per-engine health state machine + hung-dispatch monitor.

    Constructed by the engine next to its StepTimeline; the timeline
    forwards device-phase enter/exit events here (``timeline.watch``),
    which keeps the seam coverage exactly equal to the stepline's
    instrumentation — any newly instrumented device seam is watched for
    free.
    """

    def __init__(self, engine: Optional[object] = None,
                 deadline_s: Optional[float] = None,
                 quarantine_window_s: Optional[float] = None,
                 margin: float = DEFAULT_DEADLINE_MARGIN,
                 floor_s: float = DEFAULT_DEADLINE_FLOOR_S,
                 derive_deadline: bool = True,
                 clock: Callable[[], float] = time.monotonic):
        self.engine = engine
        self._clock = clock
        # False = only an explicit override (env/ctor/test) ever trips
        # the monitor; the EWMA still accumulates for observability
        self.derive_deadline = derive_deadline
        self._deadline_override = (deadline_s if deadline_s is not None
                                   else _env_deadline())
        self.quarantine_window_s = (
            quarantine_window_s if quarantine_window_s is not None
            else _env_quarantine_window())
        self.margin = margin
        self.floor_s = floor_s

        self._lock = threading.Lock()
        self._state = "healthy"  # guarded_by: _lock
        self._armed: Optional[List] = None  # guarded_by: _lock — [seam, t0, tripped]
        self._ewma_s: Optional[float] = None  # guarded_by: _lock
        self._trip_times: Deque[float] = collections.deque(maxlen=32)  # guarded_by: _lock
        self.trips_total: Dict[str, int] = {}  # guarded_by: _lock — by kind
        self.integrity_faults_total: Dict[str, int] = {}  # guarded_by: _lock — by sentinel
        self.last_trip: Optional[Dict[str, object]] = None  # guarded_by: _lock

        # hooks fired OUTSIDE the lock (serving wires shed/handoff/metrics)
        self.on_trip: Optional[Callable[[str, str], None]] = None
        self.on_health: Optional[Callable[[str], None]] = None

        self._monitor: Optional[threading.Thread] = None  # guarded_by: _lock
        self._resurrector: Optional[threading.Thread] = None  # guarded_by: _lock
        self._stop = threading.Event()

    # ------------------------------------------------------------- health --
    @property
    def health(self) -> str:
        with self._lock:
            return self._state

    @property
    def health_code(self) -> int:
        return HEALTH_CODES[self.health]

    @property
    def ok_for_traffic(self) -> bool:
        """Gate for /v1/* admission and readiness: only a healthy engine
        takes new work."""
        return self.health == "healthy"

    def _transition(self, state: str) -> bool:
        """Set health under the lock; fire on_health outside it.  A
        quarantined worker never leaves quarantine (terminal)."""
        with self._lock:
            if self._state == "quarantined" and state != "quarantined":
                return False
            if self._state == state:
                return False
            self._state = state
        log.warning("engine health -> %s", state)
        cb = self.on_health
        if cb is not None:
            try:
                cb(state)
            except Exception:
                log.exception("on_health hook failed")
        return True

    # --------------------------------------------------- seam arm / disarm --
    def device_enter(self, seam: str) -> None:
        """A device dispatch/readback seam opened (timeline hook).  Arms
        the deadline and lazily starts the monitor."""
        now = self._clock()
        with self._lock:
            self._armed = [seam, now, False]
            started = self._monitor is not None and self._monitor.is_alive()
        if not started:
            self._start_monitor()

    def device_exit(self, seam: str) -> None:
        """Seam closed in time: disarm and fold the duration into the
        EWMA the derived deadline rests on."""
        now = self._clock()
        with self._lock:
            armed = self._armed
            self._armed = None
            if armed is None or armed[2]:
                # nothing armed, or this seam already tripped — a late
                # return from a tripped seam must not poison the EWMA
                return
            dt = max(0.0, now - armed[1])
            if self._ewma_s is None:
                self._ewma_s = dt
            else:
                self._ewma_s = ((1.0 - EWMA_ALPHA) * self._ewma_s
                                + EWMA_ALPHA * dt)

    def deadline_s(self) -> float:
        """Effective per-seam deadline: env/ctor override wins, else
        EWMA x margin with a floor (pre-EWMA: just the floor)."""
        if self._deadline_override is not None:
            return self._deadline_override
        with self._lock:
            ewma = self._ewma_s
        if ewma is None:
            return self.floor_s
        return max(self.floor_s, ewma * self.margin)

    # ------------------------------------------------------------ monitor --
    def _start_monitor(self) -> None:
        with self._lock:
            if self._monitor is not None and self._monitor.is_alive():
                return
            self._monitor = threading.Thread(
                target=self._monitor_loop, name="engine-watchdog",
                daemon=True)
            self._monitor.start()

    def _monitor_loop(self) -> None:
        idle_since: Optional[float] = None
        while not self._stop.is_set():
            deadline = self.deadline_s()
            # derived deadlines only arm on real accelerators: a CPU
            # fallback recompiles mid-seam at will, so without an
            # explicit override the monitor observes but never trips
            armable = (self._deadline_override is not None
                       or self.derive_deadline)
            tripped_seam = None
            now = self._clock()
            with self._lock:
                armed = self._armed
                if (armable and armed is not None and not armed[2]
                        and now - armed[1] > deadline):
                    armed[2] = True  # one trip per arming
                    tripped_seam = armed[0]
                if armed is None:
                    if idle_since is None:
                        idle_since = now
                    elif now - idle_since > MONITOR_IDLE_EXIT_S:
                        # park: no seam armed for a while — drop the
                        # thread so a retired engine is collectible;
                        # device_enter restarts it on the next dispatch
                        self._monitor = None
                        return
                else:
                    idle_since = None
            if tripped_seam is not None:
                self.trip("hung_dispatch", seam=tripped_seam,
                          deadline_s=deadline)
            # poll an order of magnitude finer than the deadline so
            # detection latency stays << the deadline itself
            self._stop.wait(max(0.01, min(0.25, deadline / 10.0)))

    def stop(self) -> None:
        """Engine shutdown: stop the monitor thread."""
        self._stop.set()

    # --------------------------------------------------------------- trips --
    def trip(self, kind: str, seam: str = "", escalate: bool = True,
             **fields) -> None:
        """A blown deadline or fatal step.  Runs on the monitor (or
        scheduler) thread and NEVER takes the engine exec lock — the
        scheduler may be wedged under it.  Marks the worker suspect,
        flight-dumps, fires on_trip, and launches the escalation ladder
        (or quarantines on repeat trips inside the window)."""
        now = self._clock()
        with self._lock:
            recent = [t for t in self._trip_times
                      if now - t <= self.quarantine_window_s]
            self._trip_times.append(now)
            self.trips_total[kind] = self.trips_total.get(kind, 0) + 1
            self.last_trip = {"kind": kind, "seam": seam, "t": now, **fields}
            quarantine = len(recent) >= 1  # this trip is the 2nd in window
        eng = self.engine
        if eng is not None and getattr(eng, "flight", None) is not None:
            try:
                eng.flight.note("watchdog_trip", kind=kind, seam=seam,
                                **fields)
                eng.flight.dump(f"watchdog_{kind}")
            except Exception:
                log.exception("watchdog flight dump failed")
        if quarantine:
            log.error("watchdog trip kind=%s seam=%s — repeat inside "
                      "%.1fs window, quarantining permanently",
                      kind, seam, self.quarantine_window_s)
            self._transition("quarantined")
        else:
            log.error("watchdog trip kind=%s seam=%s deadline=%s",
                      kind, seam, fields.get("deadline_s"))
            self._transition("suspect")
        cb = self.on_trip
        if cb is not None:
            try:
                cb(kind, seam)
            except Exception:
                log.exception("on_trip hook failed")
        if not quarantine and escalate:
            self._start_resurrector()

    def on_fatal_step(self, err: BaseException) -> None:
        """engine_service's fatal-step path: the scheduler thread itself
        caught the error, so it is NOT wedged — trip, then resurrect
        inline on this thread (deterministic: no escalation thread, no
        window where a broken engine takes another step)."""
        self.trip("fatal_step", seam="step", escalate=False,
                  error=repr(err))
        if self.health == "suspect":
            self._resurrect()
        elif self.health == "quarantined" and self.engine is not None:
            # permanently out of rotation — still tear down the streams
            # so every waiting handler sees a final event
            try:
                self.engine.abort_all()
            except Exception:
                log.exception("quarantine teardown failed")

    def record_integrity_fault(self, sentinel: str, rids: List[str],
                               **fields) -> None:
        """A sentinel caught corruption.  Counted and flight-noted, but
        health does NOT change: the poisoned streams are aborted and the
        engine keeps serving co-batched tenants."""
        with self._lock:
            self.integrity_faults_total[sentinel] = (
                self.integrity_faults_total.get(sentinel, 0) + 1)
        eng = self.engine
        if eng is not None and getattr(eng, "flight", None) is not None:
            try:
                eng.flight.note("integrity_fault", sentinel=sentinel,
                                rids=list(rids), **fields)
            except Exception:
                log.exception("integrity flight note failed")
        log.error("integrity fault sentinel=%s rids=%s", sentinel,
                  list(rids))

    # --------------------------------------------------------- escalation --
    def _start_resurrector(self) -> None:
        with self._lock:
            if self._resurrector is not None and self._resurrector.is_alive():
                return
            self._resurrector = threading.Thread(
                target=self._resurrect, name="engine-resurrector",
                daemon=True)
            self._resurrector.start()

    def _resurrect(self) -> None:
        """Escalation ladder tail: block until the wedged dispatch
        returns control (RLock), then rebuild device state in place.  A
        real device hang never returns the lock — the worker stays
        suspect and shedding until the operator replaces the pod."""
        eng = self.engine
        if eng is None:
            return
        lock = getattr(eng, "_exec_lock", None)
        try:
            if lock is not None:
                lock.acquire()
            try:
                if self.health == "quarantined":
                    return
                self._transition("resurrecting")
                eng.resurrect()
            finally:
                if lock is not None:
                    lock.release()
        except Exception:
            log.exception("engine resurrection failed — quarantining")
            self._transition("quarantined")
            return
        if self._transition("healthy"):
            log.warning("engine resurrected in place; serving again")

    # ----------------------------------------------------------- snapshot --
    def summary(self) -> Dict[str, object]:
        """Rides /worker/stats and the heartbeat (frontend health gauge,
        router filter)."""
        with self._lock:
            if self._deadline_override is not None:
                deadline = self._deadline_override
            elif self._ewma_s is None:
                deadline = self.floor_s
            else:
                deadline = max(self.floor_s, self._ewma_s * self.margin)
            return {
                "state": self._state,
                "code": HEALTH_CODES[self._state],
                "trips_total": dict(self.trips_total),
                "integrity_faults_total": dict(self.integrity_faults_total),
                "ewma_s": self._ewma_s,
                "deadline_s": deadline,
                "last_trip": dict(self.last_trip) if self.last_trip else None,
            }
