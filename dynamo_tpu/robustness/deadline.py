"""End-to-end deadline propagation.

A request's time budget is decided ONCE — by the client's ``x-deadline``
header (remaining seconds) or the ``DYNAMO_TPU_DEADLINE_S`` default — and
then RIDES the request: frontend -> worker (HTTP header or NATS message
header) -> decode -> prefill RPC. Each hop constructs a `Deadline` when
the request arrives and forwards ``remaining()`` downstream, so queueing
and transfer time anywhere in the path shrinks the budget everywhere
after it. The wire format is *relative seconds*, not an absolute
timestamp, so cross-host clock skew cannot corrupt the budget.

An exhausted budget sheds load EARLY — 504 + Retry-After before taking an
engine slot — instead of holding resources for an answer the client has
already given up on. The hard-coded ``timeout=600`` / ``timeout=300``
socket timeouts in the frontend proxy, the NATS plane, and the disagg
prefill RPC all derive from the propagated budget now.

The header may only SHRINK the budget: a client asking for more than the
operator's ``DYNAMO_TPU_DEADLINE_S`` is clamped to it (the env var is the
operator's statement of the longest request worth holding a slot for).
"""

from __future__ import annotations

import os
import time
from typing import Callable, Mapping, Optional

DEADLINE_HEADER = "x-deadline"
ENV_DEFAULT = "DYNAMO_TPU_DEADLINE_S"
DEFAULT_BUDGET_S = 600.0

# floor for derived socket timeouts: 0 would mean "non-blocking", not
# "already late" — expiry is checked explicitly before every dial
MIN_TIMEOUT_S = 0.05


def default_budget_s() -> float:
    try:
        v = float(os.environ.get(ENV_DEFAULT, DEFAULT_BUDGET_S))
        return v if v > 0 else DEFAULT_BUDGET_S
    except ValueError:
        return DEFAULT_BUDGET_S


class Deadline:
    """A monotonic countdown started when the request reached this hop."""

    __slots__ = ("budget_s", "_t0", "_clock")

    def __init__(self, budget_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.budget_s = max(0.0, float(budget_s))
        self._clock = clock
        self._t0 = clock()

    @classmethod
    def from_headers(cls, headers: Optional[Mapping],
                     clock: Callable[[], float] = time.monotonic
                     ) -> "Deadline":
        """Parse the inbound ``x-deadline`` header (remaining seconds);
        absent/invalid values get the env default; oversized values are
        clamped to it."""
        budget = default_budget_s()
        raw = headers.get(DEADLINE_HEADER) if headers is not None else None
        if raw:
            try:
                budget = min(float(raw), budget)
            except ValueError:
                pass
        return cls(budget, clock=clock)

    def remaining(self) -> float:
        return max(0.0, self.budget_s - (self._clock() - self._t0))

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def timeout(self, floor: float = MIN_TIMEOUT_S) -> float:
        """The socket/poll timeout for a downstream call made NOW."""
        return max(floor, self.remaining())

    def header_value(self) -> str:
        return f"{self.remaining():.3f}"

    def propagate(self, headers: dict) -> dict:
        """Stamp the remaining budget onto an outbound header dict."""
        headers[DEADLINE_HEADER] = self.header_value()
        return headers
