"""Per-worker circuit breakers with half-open probes.

The frontend's reactive failover only helps AFTER a request has already
paid for a dead worker's connect timeout; a flapping worker keeps
collecting fresh requests between heartbeat expiries. The breaker makes
the router *proactive*: consecutive connect/timeout failures open the
breaker and the worker stops being a routing candidate immediately; after
a cooldown one probe request is let through (half-open) and its outcome
closes or re-opens the breaker.

State machine (classic three-state):

    closed --[threshold consecutive failures]--> open
    open   --[cooldown elapsed]---------------> half_open
    half_open --[probe success]---------------> closed
    half_open --[probe failure]---------------> open (cooldown restarts)

Wired in `serving.router.Router.pick` (candidate filter + probe
admission) and `serving.frontend` (success/failure reports, /metrics
export, `router.pick` span attributes). Env knobs:

- ``DYNAMO_TPU_BREAKER_THRESHOLD`` (default 3) consecutive failures to open;
- ``DYNAMO_TPU_BREAKER_COOLDOWN_S`` (default 5.0) open->half-open delay.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Optional

ENV_THRESHOLD = "DYNAMO_TPU_BREAKER_THRESHOLD"
ENV_COOLDOWN = "DYNAMO_TPU_BREAKER_COOLDOWN_S"
DEFAULT_THRESHOLD = 3
DEFAULT_COOLDOWN_S = 5.0

# /metrics encoding of the state (docs/robustness.md)
STATE_CODES = {"closed": 0, "half_open": 1, "open": 2}


def _env_threshold() -> int:
    try:
        return max(1, int(os.environ.get(ENV_THRESHOLD, DEFAULT_THRESHOLD)))
    except ValueError:
        return DEFAULT_THRESHOLD


def _env_cooldown() -> float:
    try:
        return max(0.0, float(os.environ.get(ENV_COOLDOWN,
                                             DEFAULT_COOLDOWN_S)))
    except ValueError:
        return DEFAULT_COOLDOWN_S


class CircuitBreaker:
    """One worker's breaker. Not thread-safe on its own — the owning
    BreakerBoard serializes access."""

    def __init__(self, threshold: int, cooldown_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self.failures = 0          # consecutive, while closed
        self.opened_at: Optional[float] = None
        self.probe_at: Optional[float] = None  # half-open probe in flight
        # a lost probe (picked worker never reported back) must not wedge
        # the breaker open forever — after this long assume it died and
        # allow another probe
        self.probe_timeout_s = max(30.0, cooldown_s)

    @property
    def state(self) -> str:
        if self.opened_at is None:
            return "closed"
        if self._clock() - self.opened_at >= self.cooldown_s:
            return "half_open"
        return "open"

    def available(self) -> bool:
        """May this worker be a routing candidate right now?"""
        st = self.state
        if st == "closed":
            return True
        if st == "open":
            return False
        # half-open: exactly one probe at a time
        if self.probe_at is None:
            return True
        return self._clock() - self.probe_at >= self.probe_timeout_s

    def take_probe(self) -> None:
        if self.state == "half_open":
            self.probe_at = self._clock()

    def record_success(self) -> None:
        self.failures = 0
        self.opened_at = None
        self.probe_at = None

    def record_failure(self) -> bool:
        """Returns True when this failure OPENED the breaker (either the
        threshold trip or a failed half-open probe)."""
        if self.opened_at is not None:
            # open or half-open: any failure (re)starts the cooldown
            reopened = self.state == "half_open"
            self.opened_at = self._clock()
            self.probe_at = None
            return reopened
        self.failures += 1
        if self.failures >= self.threshold:
            self.opened_at = self._clock()
            self.probe_at = None
            return True
        return False


class BreakerBoard:
    """All workers' breakers, keyed by worker URL. Breakers survive
    deregistration on purpose: a dead worker that re-registers via a racing
    heartbeat stays quarantined until its probe succeeds."""

    def __init__(self, threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_open: Optional[Callable[[str], None]] = None):
        self.threshold = threshold if threshold is not None else _env_threshold()
        self.cooldown_s = (cooldown_s if cooldown_s is not None
                           else _env_cooldown())
        self._clock = clock
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}
        self.on_open = on_open  # metrics hook: called OUTSIDE the lock

    def _get(self, url: str, create: bool = False
             ) -> Optional[CircuitBreaker]:
        b = self._breakers.get(url)
        if b is None and create:
            b = self._breakers[url] = CircuitBreaker(
                self.threshold, self.cooldown_s, self._clock)
        return b

    # ------------------------------------------------------- router surface --
    def would_allow(self, url: str) -> bool:
        """Candidate filter — no side effects (pick() may evaluate many
        candidates; only the picked one consumes a probe slot)."""
        with self._lock:
            b = self._breakers.get(url)
            return b is None or b.available()

    def on_picked(self, url: str) -> None:
        with self._lock:
            b = self._breakers.get(url)
            if b is not None:
                b.take_probe()

    # ----------------------------------------------------- outcome reporting --
    def record_success(self, url: str) -> None:
        with self._lock:
            b = self._breakers.get(url)
            if b is not None:
                b.record_success()

    def record_failure(self, url: str) -> None:
        with self._lock:
            opened = self._get(url, create=True).record_failure()
        if opened and self.on_open is not None:
            try:
                self.on_open(url)
            except Exception:  # a metrics hook must never break routing
                pass

    # ---------------------------------------------------------- introspection
    def state(self, url: str) -> str:
        with self._lock:
            b = self._breakers.get(url)
            return "closed" if b is None else b.state

    def snapshot(self) -> Dict[str, str]:
        with self._lock:
            return {url: b.state for url, b in self._breakers.items()}

    def forget(self, url: str) -> None:
        with self._lock:
            self._breakers.pop(url, None)
