"""Deterministic fault-injection plane.

The serving path's failure handling (bounded failover, circuit breakers,
deadline shedding, NATS fallback) is only trustworthy if every branch can
be exercised ON DEMAND, deterministically, in tests — waiting for a real
worker to crash mid-decode proves nothing on a laptop. This module
compiles named **fault points** into the hot path; each is a no-op (one
dict lookup) until armed.

Registry of fault points (the names are the contract — docs/robustness.md):

======================================  =======================================
name                                    effect at the instrumented site
======================================  =======================================
frontend.connect_refused                frontend->worker dial raises
                                        connection-refused (pre-send, so the
                                        bounded-failover re-pick is exercised)
worker.read_stall                       worker handler sleeps ``delay_s``
                                        before processing (deadline shedding /
                                        frontend read-timeout path)
worker.reset_after_headers              worker sends status+headers then
                                        RST-closes the socket (the
                                        never-retry-after-send invariant)
worker.slow_prefill                     engine admission sleeps ``delay_s``
                                        (agg submit and /disagg/prefill)
worker.crash_mid_decode                 the token stream dies after a token
                                        was already delivered; the request is
                                        aborted engine-side (the frontend
                                        splices a journaled continuation on
                                        another worker, or truncates — never
                                        re-runs the whole generation)
nats.partition                          NATS publishes raise ConnectionError
                                        (frontend falls back to HTTP; worker
                                        responders fail their reply stream)
disagg.prefill_connect_refused          decode->prefill RPC raises
                                        connection-refused before any KV moves
                                        (prefill-pool failover)
engine.device_hang                      engine dispatch seam sleeps ``delay_s``
                                        with the exec lock held — a wedged
                                        device program (watchdog trip,
                                        quarantine ladder)
engine.device_nan                       prefill logits are poisoned with NaN
                                        before sampling (integrity sentinel:
                                        poisoned streams abort, co-batched
                                        tenants survive byte-identical)
engine.device_slow                      decode readback sleeps ``delay_s``
                                        WITHOUT tripping (sub-deadline
                                        slowness must not false-positive)
======================================  =======================================

Determinism: every probabilistic draw comes from a per-fault-point
``random.Random(f"{seed}:{name}")``, so the fire/skip decision at check N
is a pure function of (seed, spec, N) — re-running a chaos test with the
same seed replays the same faults in the same places. `make chaos-check`
pins the seed.

Configuration:
- env: ``DYNAMO_TPU_FAULTS='{"frontend.connect_refused": {"times": 1}}'``
  (JSON: name -> spec fields), ``DYNAMO_TPU_FAULT_SEED=<int>``;
- HTTP: ``GET/POST /internal/faults`` on the frontend and every worker
  (POST body ``{"seed": N, "faults": {...}}``; ``{"faults": {}}`` disarms).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import threading
import time
from typing import Dict, Mapping, Optional

log = logging.getLogger("dynamo_tpu.faults")

ENV_FAULTS = "DYNAMO_TPU_FAULTS"
ENV_SEED = "DYNAMO_TPU_FAULT_SEED"

# name -> one-line description; configure() rejects names outside this
# registry so a typo'd chaos spec fails loudly instead of silently
# injecting nothing
REGISTRY: Dict[str, str] = {
    "frontend.connect_refused":
        "frontend->worker dial fails pre-send (connection refused)",
    "worker.read_stall":
        "worker handler stalls delay_s before processing the request",
    "worker.reset_after_headers":
        "worker RST-closes the connection right after the response headers",
    "worker.slow_prefill":
        "engine admission sleeps delay_s (slow prefill)",
    "worker.crash_mid_decode":
        "token stream dies after delivery started; request aborted "
        "(recovery plane splices a continuation, else truncate)",
    "nats.partition":
        "NATS publishes raise ConnectionError (plane partition)",
    "disagg.prefill_connect_refused":
        "decode->prefill RPC fails pre-send (connection refused)",
    "engine.device_hang":
        "engine dispatch seam wedges delay_s with the exec lock held "
        "(watchdog trip, resurrection/quarantine ladder)",
    "engine.device_nan":
        "prefill logits poisoned with NaN pre-sampling (integrity "
        "sentinel aborts exactly the poisoned streams)",
    "engine.device_slow":
        "decode readback sleeps delay_s without tripping (sub-deadline "
        "slowness is not a hang)",
}


@dataclasses.dataclass
class FaultSpec:
    """How one armed fault point fires.

    - ``times``: fire at most this many times (-1 = unlimited);
    - ``p``: per-check fire probability (seeded draw when < 1.0);
    - ``after``: skip the first N checks (lets a test warm a path up
      before breaking it);
    - ``delay_s``: sleep duration for the stall/slow faults.
    """

    times: int = 1
    p: float = 1.0
    after: int = 0
    delay_s: float = 0.0

    @classmethod
    def from_dict(cls, d: Mapping) -> "FaultSpec":
        allowed = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - allowed
        if unknown:
            raise ValueError(f"unknown fault spec fields: {sorted(unknown)}")
        return cls(**{k: type(getattr(cls, k))(v) for k, v in d.items()})

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)


class FaultPlane:
    """Process-global registry of armed fault points + fire accounting."""

    def __init__(self, seed: Optional[int] = None):
        self._lock = threading.Lock()
        self._specs: Dict[str, FaultSpec] = {}
        self._rngs: Dict[str, random.Random] = {}
        self._checks: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}
        # cumulative across configure() calls — a chaos suite re-arms
        # between tests and asserts total coverage at the end
        self._fired_total: Dict[str, int] = {}
        if seed is None:
            try:
                seed = int(os.environ.get(ENV_SEED, "0"))
            except ValueError:
                seed = 0
        self.seed = seed
        env_spec = os.environ.get(ENV_FAULTS)
        if env_spec:
            try:
                self.configure(json.loads(env_spec))
            except (ValueError, json.JSONDecodeError) as e:
                log.warning("ignoring invalid %s: %s", ENV_FAULTS, e)

    # ----------------------------------------------------------- configure --
    def configure(self, faults: Mapping[str, Mapping],
                  seed: Optional[int] = None, replace: bool = True) -> None:
        """Arm the given fault points (name -> spec dict). Unknown names
        raise. Per-point check/fire counters and RNGs reset for the
        configured points; cumulative fire totals survive."""
        specs = {}
        for name, spec in faults.items():
            if name not in REGISTRY:
                raise ValueError(
                    f"unknown fault point {name!r} (known: "
                    f"{sorted(REGISTRY)})")
            specs[name] = (spec if isinstance(spec, FaultSpec)
                           else FaultSpec.from_dict(spec))
        with self._lock:
            if seed is not None:
                self.seed = seed
            if replace:
                self._specs = specs
            else:
                self._specs.update(specs)
            for name in specs:
                self._rngs[name] = random.Random(f"{self.seed}:{name}")
                self._checks[name] = 0
                self._fired[name] = 0

    def arm(self, name: str, **spec) -> None:
        self.configure({name: spec}, replace=False)

    def clear(self) -> None:
        with self._lock:
            self._specs = {}

    # --------------------------------------------------------------- firing --
    def check(self, name: str) -> Optional[FaultSpec]:
        """The instrumented-site call: returns the spec when this check
        fires, else None. No-op-cheap when the point isn't armed."""
        if not self._specs:  # fast path: nothing armed anywhere
            return None
        with self._lock:
            spec = self._specs.get(name)
            if spec is None:
                return None
            idx = self._checks.get(name, 0)
            self._checks[name] = idx + 1
            if idx < spec.after:
                return None
            if 0 <= spec.times <= self._fired.get(name, 0):
                return None
            if spec.p < 1.0 and self._rngs[name].random() >= spec.p:
                return None
            self._fired[name] = self._fired.get(name, 0) + 1
            self._fired_total[name] = self._fired_total.get(name, 0) + 1
        log.info("fault injected: %s (fire #%d)", name, self._fired[name])
        return spec

    # ---------------------------------------------------------- introspection
    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "seed": self.seed,
                "armed": {n: s.to_dict() for n, s in self._specs.items()},
                "checks": dict(self._checks),
                "fired": dict(self._fired),
                "fired_total": dict(self._fired_total),
                "registry": dict(REGISTRY),
            }


_plane: Optional[FaultPlane] = None
_plane_lock = threading.Lock()


def get_plane() -> FaultPlane:
    global _plane
    if _plane is None:
        with _plane_lock:
            if _plane is None:
                _plane = FaultPlane()
    return _plane


def reset_plane(seed: Optional[int] = None) -> FaultPlane:
    """Fresh plane (tests): drops armed specs AND cumulative counters."""
    global _plane
    with _plane_lock:
        _plane = FaultPlane(seed=seed)
    return _plane


# ------------------------- site helpers (the instrumented-path surface) ----
def check(name: str) -> Optional[FaultSpec]:
    return get_plane().check(name)


def sleep_point(name: str) -> bool:
    """Delay-type fault site: sleeps spec.delay_s when armed. Returns
    whether it fired (sites can annotate spans)."""
    spec = get_plane().check(name)
    if spec is None:
        return False
    if spec.delay_s > 0:
        time.sleep(spec.delay_s)
    return True


def raise_point(name: str, exc_factory) -> None:
    """Raise-type fault site: raises exc_factory(message) when armed."""
    spec = get_plane().check(name)
    if spec is not None:
        raise exc_factory(f"injected fault: {name}")


def http_payload() -> Dict:
    """GET /internal/faults body."""
    return get_plane().snapshot()


def http_configure(body: Mapping) -> Dict:
    """POST /internal/faults: {"seed": N?, "faults": {name: spec}}.
    Raises ValueError on unknown names/fields (mapped to HTTP 400)."""
    faults = body.get("faults")
    if not isinstance(faults, Mapping):
        raise ValueError('body must carry "faults": {name: spec}')
    seed = body.get("seed")
    get_plane().configure(faults, seed=None if seed is None else int(seed))
    return get_plane().snapshot()
