"""W3C Trace Context: `traceparent` parse/format + ID generation.

The header format is the 4-field version-00 form
(https://www.w3.org/TR/trace-context/):

    traceparent: 00-<32 lowercase hex trace-id>-<16 hex parent-id>-<2 hex flags>

Only version 00 is emitted; any version byte other than `ff` is accepted
(the spec requires forward compatibility: a later version's first four
fields parse the same way, extra fields are ignored).

ID generation is deterministic when a seed is supplied: the same request id
maps to the same trace id on every hop, so a trace survives even a transport
that drops the header (the NATS fallback path, a misbehaving proxy) — the
worker re-derives the identical trace id from `x-request-id` and the spans
still join up in the collector.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import re
from typing import Dict, Mapping, Optional

TRACEPARENT_HEADER = "traceparent"
REQUEST_ID_HEADER = "x-request-id"

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})(?:-.*)?$"
)


def new_trace_id(seed: Optional[str] = None) -> str:
    """32 lowercase hex chars; derived from `seed` when given (deterministic
    across processes), random otherwise. Never all-zero (invalid per spec)."""
    if seed:
        tid = hashlib.sha256(b"trace\x00" + seed.encode("utf-8", "replace")
                             ).hexdigest()[:32]
    else:
        tid = os.urandom(16).hex()
    return tid if tid != "0" * 32 else "1" * 32


def new_span_id(seed: Optional[str] = None) -> str:
    """16 lowercase hex chars; seeded variant for deterministic tests."""
    if seed:
        sid = hashlib.sha256(b"span\x00" + seed.encode("utf-8", "replace")
                             ).hexdigest()[:16]
    else:
        sid = os.urandom(8).hex()
    return sid if sid != "0" * 16 else "1" * 16


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """An extracted/minted trace position: the parent coordinates a new span
    attaches under."""

    trace_id: str
    span_id: str
    flags: int = 1  # sampled

    def to_traceparent(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-{self.flags:02x}"

    @staticmethod
    def new(seed: Optional[str] = None) -> "TraceContext":
        return TraceContext(new_trace_id(seed), new_span_id(seed))


def parse_traceparent(value: Optional[str]) -> Optional[TraceContext]:
    """Strict-enough parse: None on anything malformed (a bad inbound header
    must start a fresh trace, never corrupt ours)."""
    if not value:
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    version, trace_id, span_id, flags = m.groups()
    if version == "ff":  # forbidden version value
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return TraceContext(trace_id, span_id, int(flags, 16))


def format_traceparent(ctx: TraceContext) -> str:
    return ctx.to_traceparent()


def extract_context(headers: Optional[Mapping],
                    request_id: Optional[str] = None) -> Optional[TraceContext]:
    """Pull a TraceContext out of HTTP-ish headers (any case-insensitive
    mapping with .get, e.g. http.client.HTTPMessage). Falls back to deriving
    a deterministic trace id from `x-request-id` (or the explicit
    `request_id`), so correlation survives header-stripping transports;
    returns None when there is nothing to join."""
    if headers is not None:
        ctx = parse_traceparent(headers.get(TRACEPARENT_HEADER))
        if ctx is not None:
            return ctx
        request_id = request_id or headers.get(REQUEST_ID_HEADER)
    if request_id:
        return TraceContext(new_trace_id(request_id),
                            new_span_id(request_id))
    return None


def inject_context(ctx: Optional[TraceContext], headers: Dict[str, str],
                   request_id: Optional[str] = None) -> Dict[str, str]:
    """Write traceparent (+ x-request-id when given) into a header dict;
    returns the dict for call-site chaining."""
    if ctx is not None:
        headers[TRACEPARENT_HEADER] = ctx.to_traceparent()
    if request_id:
        headers[REQUEST_ID_HEADER] = request_id
    return headers
