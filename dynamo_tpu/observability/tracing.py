"""Tracer/Span + ring-buffer collector, OTLP-JSON-shaped export.

Design constraints (ISSUE 1 acceptance criteria):
- stdlib only — no opentelemetry dependency; the export dicts are shaped
  like OTLP/JSON `ExportTraceServiceRequest` so a real collector can ingest
  them unchanged later;
- bounded memory — one process-global deque (default 2048 spans,
  `DYNAMO_TPU_TRACE_BUFFER` overrides) shared by every Tracer in the
  process; 10k traced requests grow the heap by zero;
- kill switch — `DYNAMO_TPU_TRACE=0` makes `start_span` return the no-op
  singleton before any allocation (checked per call, so tests and live
  operators can flip it without restarting).

One collector per PROCESS, one Tracer per service role: a test process
hosting frontend + prefill + decode servers sees the whole trace from any
server's /debug/spans; in a real deployment each pod naturally exposes its
own slice and the trace id joins them across scrapes.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Union

from dynamo_tpu.observability.context import TraceContext, new_span_id, new_trace_id

DEFAULT_BUFFER_SPANS = 2048

_KIND_CODES = {  # OTLP SpanKind enum values
    "internal": 1, "server": 2, "client": 3, "producer": 4, "consumer": 5,
}


def tracing_enabled() -> bool:
    return os.environ.get("DYNAMO_TPU_TRACE", "1").lower() not in (
        "0", "false", "off", "no")


# requests slower than this log a WARNING carrying their trace id — the
# exemplar-style bridge from the latency histograms to /debug/spans
SLOW_REQUEST_ENV = "DYNAMO_TPU_SLOW_REQUEST_S"
DEFAULT_SLOW_REQUEST_S = 10.0


def slow_request_threshold_s() -> float:
    try:
        return float(os.environ.get(SLOW_REQUEST_ENV,
                                    DEFAULT_SLOW_REQUEST_S))
    except ValueError:
        return DEFAULT_SLOW_REQUEST_S


def _otlp_value(v: Any) -> Dict[str, Any]:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}  # OTLP/JSON encodes int64 as string
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def _otlp_attrs(attrs: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [{"key": k, "value": _otlp_value(v)} for k, v in attrs.items()]


class Span:
    """One timed operation. Context-manager friendly:

        with tracer.start_span("router.pick", parent=ctx) as span:
            span.set_attribute("worker.url", url)

    `end()` is idempotent; the span reaches the collector exactly once, at
    first end. Attribute/event mutation after end is dropped silently (a
    late background thread must not resurrect an exported span)."""

    __slots__ = ("name", "trace_id", "span_id", "parent_span_id", "kind",
                 "service", "start_ns", "end_ns", "attributes", "events",
                 "status_code", "status_message", "_collector", "_ended")

    recording = True

    def __init__(self, name: str, trace_id: str, span_id: str,
                 parent_span_id: Optional[str], kind: str, service: str,
                 collector: "SpanCollector", start_ns: Optional[int] = None,
                 attributes: Optional[Dict[str, Any]] = None):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span_id = parent_span_id
        self.kind = kind
        self.service = service
        self.start_ns = time.time_ns() if start_ns is None else start_ns
        self.end_ns: Optional[int] = None
        self.attributes: Dict[str, Any] = dict(attributes or {})
        self.events: List[Dict[str, Any]] = []
        self.status_code = "UNSET"
        self.status_message = ""
        self._collector = collector
        self._ended = False

    # ------------------------------------------------------------- mutation
    def set_attribute(self, key: str, value: Any) -> "Span":
        if not self._ended:
            self.attributes[key] = value
        return self

    def set_attributes(self, attrs: Dict[str, Any]) -> "Span":
        if not self._ended:
            self.attributes.update(attrs)
        return self

    def add_event(self, name: str,
                  attributes: Optional[Dict[str, Any]] = None) -> "Span":
        if not self._ended:
            self.events.append({"name": name, "time_ns": time.time_ns(),
                                "attributes": dict(attributes or {})})
        return self

    def set_status(self, code: str, message: str = "") -> "Span":
        if not self._ended:
            self.status_code = code  # "OK" | "ERROR" | "UNSET"
            self.status_message = message
        return self

    def end(self, end_ns: Optional[int] = None) -> None:
        if self._ended:
            return
        self._ended = True
        self.end_ns = time.time_ns() if end_ns is None else end_ns
        if self.end_ns < self.start_ns:  # clock nonsense must not export
            self.end_ns = self.start_ns  # a negative-duration span
        self._collector.add(self)

    # -------------------------------------------------------------- plumbing
    @property
    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id)

    def __enter__(self) -> "Span":
        return self

    def __exit__(self, exc_type, exc, _tb) -> None:
        if exc is not None and not self._ended:
            self.set_status("ERROR", f"{exc_type.__name__}: {exc}")
        self.end()

    def to_otlp(self) -> Dict[str, Any]:
        return {
            "traceId": self.trace_id,
            "spanId": self.span_id,
            "parentSpanId": self.parent_span_id or "",
            "name": self.name,
            "kind": _KIND_CODES.get(self.kind, 1),
            "startTimeUnixNano": str(self.start_ns),
            "endTimeUnixNano": str(self.end_ns or self.start_ns),
            "attributes": _otlp_attrs(self.attributes),
            "events": [
                {"name": e["name"], "timeUnixNano": str(e["time_ns"]),
                 "attributes": _otlp_attrs(e["attributes"])}
                for e in self.events
            ],
            "status": ({"code": 2, "message": self.status_message}
                       if self.status_code == "ERROR"
                       else {"code": 1 if self.status_code == "OK" else 0}),
        }


class _NoopSpan:
    """The kill-switch singleton: absorbs the whole Span surface without
    allocating. Its `context` is None — propagation falls back to whatever
    inbound context the caller already holds."""

    recording = False
    context: Optional[TraceContext] = None
    trace_id = ""
    span_id = ""

    def set_attribute(self, *_a, **_k):
        return self

    def set_attributes(self, *_a, **_k):
        return self

    def add_event(self, *_a, **_k):
        return self

    def set_status(self, *_a, **_k):
        return self

    def end(self, *_a, **_k):
        return None

    def __enter__(self):
        return self

    def __exit__(self, *_a):
        return None


NOOP_SPAN = _NoopSpan()


class SpanCollector:
    """Bounded in-memory span sink (a deque ring buffer: the newest
    `capacity` finished spans win; old traces age out instead of growing
    the heap)."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            try:
                capacity = int(os.environ.get("DYNAMO_TPU_TRACE_BUFFER",
                                              DEFAULT_BUFFER_SPANS))
            except ValueError:
                capacity = DEFAULT_BUFFER_SPANS
        self.capacity = max(1, capacity)
        self._spans: "collections.deque[Span]" = collections.deque(
            maxlen=self.capacity)
        # spans evicted by ring wrap-around — previously a SILENT loss; now
        # `dynamo_spans_dropped_total` on /metrics, so "exemplar link
        # resolves to nothing" is diagnosable as buffer churn
        self.dropped_total = 0
        self._lock = threading.Lock()

    def __len__(self) -> int:
        with self._lock:
            return len(self._spans)

    def add(self, span: Span) -> None:
        with self._lock:
            if len(self._spans) == self.capacity:
                self.dropped_total += 1
            self._spans.append(span)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def snapshot(self, trace_id: Optional[str] = None,
                 service: Optional[str] = None,
                 name_prefix: Optional[str] = None) -> List[Span]:
        with self._lock:
            spans = list(self._spans)
        if trace_id:
            spans = [s for s in spans if s.trace_id == trace_id]
        if service:
            spans = [s for s in spans if s.service == service]
        if name_prefix:
            spans = [s for s in spans if s.name.startswith(name_prefix)]
        return spans

    def export(self, trace_id: Optional[str] = None,
               service: Optional[str] = None,
               name_prefix: Optional[str] = None) -> Dict[str, Any]:
        """OTLP/JSON `ExportTraceServiceRequest` shape: spans grouped into
        one resourceSpans entry per service name."""
        by_service: Dict[str, List[Span]] = {}
        for s in self.snapshot(trace_id, service, name_prefix):
            by_service.setdefault(s.service, []).append(s)
        return {
            "resourceSpans": [
                {
                    "resource": {"attributes": _otlp_attrs(
                        {"service.name": svc})},
                    "scopeSpans": [{
                        "scope": {"name": "dynamo_tpu.observability"},
                        "spans": [s.to_otlp() for s in spans],
                    }],
                }
                for svc, spans in sorted(by_service.items())
            ]
        }

    def trace_ids(self, limit: int = 64) -> List[str]:
        """Most-recent-first distinct trace ids (the /debug/spans index)."""
        out: List[str] = []
        seen = set()
        for s in reversed(self.snapshot()):
            if s.trace_id not in seen:
                seen.add(s.trace_id)
                out.append(s.trace_id)
                if len(out) >= limit:
                    break
        return out


_GLOBAL_COLLECTOR = SpanCollector()


def get_collector() -> SpanCollector:
    return _GLOBAL_COLLECTOR


class Tracer:
    """Span factory for one service role (frontend / worker-decode / ...).
    All tracers in a process share the global collector unless given their
    own (tests isolate with an explicit SpanCollector)."""

    def __init__(self, service: str,
                 collector: Optional[SpanCollector] = None):
        self.service = service
        # explicit None check: an EMPTY collector is falsy (__len__ == 0)
        # and `or` would silently swap in the global one
        self.collector = (collector if collector is not None
                          else _GLOBAL_COLLECTOR)

    def start_span(
        self,
        name: str,
        parent: Union[TraceContext, Span, None] = None,
        kind: str = "internal",
        attributes: Optional[Dict[str, Any]] = None,
        trace_seed: Optional[str] = None,
        start_ns: Optional[int] = None,
    ) -> Union[Span, _NoopSpan]:
        """`parent` may be a TraceContext (remote parent), a Span (local
        parent), or None (new root; `trace_seed` makes the root trace id
        deterministic — derived from the request id)."""
        if not tracing_enabled():
            return NOOP_SPAN
        if isinstance(parent, _NoopSpan):
            parent = None  # a noop parent parents nothing: new root
        elif isinstance(parent, Span):
            parent = parent.context
        if parent is not None:
            trace_id, parent_span_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_span_id = new_trace_id(trace_seed), None
        return Span(name, trace_id, new_span_id(), parent_span_id, kind,
                    self.service, self.collector, start_ns=start_ns,
                    attributes=attributes)


def spans_debug_payload(qs: Dict[str, List[str]],
                        collector: Optional[SpanCollector] = None
                        ) -> Dict[str, Any]:
    """Shared `GET /debug/spans` body builder (frontend + worker servers):
    honors ?trace_id=, ?service= and ?name= (span-name prefix) filters and
    always carries the recent trace-id index so operators can discover
    what to filter by."""
    collector = collector if collector is not None else get_collector()
    trace_id = (qs.get("trace_id") or [None])[0]
    service = (qs.get("service") or [None])[0]
    name_prefix = (qs.get("name") or [None])[0]
    payload = collector.export(trace_id=trace_id, service=service,
                               name_prefix=name_prefix)
    payload["traceIds"] = collector.trace_ids()
    payload["enabled"] = tracing_enabled()
    payload["capacity"] = collector.capacity
    payload["droppedTotal"] = collector.dropped_total
    return payload


def iter_otlp_spans(payload: Dict[str, Any]) -> Iterable[Dict[str, Any]]:
    """Flatten an export payload back to span dicts (test/tooling helper)."""
    for rs in payload.get("resourceSpans", []):
        for ss in rs.get("scopeSpans", []):
            for sp in ss.get("spans", []):
                yield sp
