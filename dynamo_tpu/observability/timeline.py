"""Stepline: unified per-step timeline, host-bubble accounting, Perfetto
export.

The ROADMAP's zero-bubble engine-loop item is gated on measurement:
"acceptance = phase accounting shows inter-dispatch host gap near zero".
This module is that measurement substrate — an always-on, low-overhead
per-step timeline the engine's `step()` feeds with precise monotonic
phase intervals:

- ``admit``       — scheduling/admission host work (aborts, queue picks,
                    adapter resolution, prefix lookups, slot install);
- ``page_alloc``  — KV page provisioning (allocator, eviction, preempt);
- ``dispatch``    — host time launching device programs (arg staging,
                    jit call until control returns);
- ``device_wait`` — blocking readback of device results (np.asarray on
                    program outputs, first-token sampling sync);
- ``detok``       — token-event production: stop checks, host mirrors,
                    logprob decoration, slot teardown;
- ``bank``        — end-of-step accounting (QoS budgets, flight commit).

Phases nest with *pause* semantics: entering an inner phase closes the
outer phase's open segment and reopens it on exit, so every recorded
interval is exclusive self-time and the per-step segments are disjoint
by construction.  Conservation therefore holds exactly:
``sum(phase self-times) + gap = step wall time``, where ``gap`` is the
host time no instrumented phase claimed.

Separately, each ``dispatch`` entry samples the **inter-dispatch host
gap** — wall time between device program N returning control and
program N+1 launching (clamped at 0: async scheduling legitimately
dispatches window N+1 before materializing window N).  This is the
number the zero-bubble PR must drive to ~0; it exports as
``dynamo_engine_host_gap_seconds`` and the per-phase digests ride the
existing ``dynamo_engine_phase_seconds{phase}`` histogram as additional
label values (observability/engine_metrics.py).

Record keeping follows the flight recorder's single-writer draft
pattern: `Engine.step()` runs under `_exec_lock` on one scheduler
thread, so the draft and phase stack are touched lock-free; the only
lock is a tiny mutex around ring append/snapshot.  Exact interval
records keep BOTH a monotonic anchor (interval math) and a
``time.time_ns`` wall anchor, so the Perfetto export shares a clock
domain with the request spans in observability/tracing.py (which are
``time_ns`` natively) — one Chrome Trace Event JSON file shows a
request end-to-end through the engine.

Exposure:

- ``GET /debug/timeline?steps=N&format=perfetto|summary|json`` on every
  worker (`timeline_debug_payload`);
- ``StepTimeline.summary()`` rides `/worker/stats` and the worker
  heartbeat, so frontends roll the bubble attribution up fleet-wide
  (`merge_summaries`) without scrape fan-out — same pattern as the
  per-tenant cost ledger;
- `scripts/dynamo_top.py` renders the per-worker phase/bubble panel.

Knobs: ``DYNAMO_TPU_TIMELINE`` (0/false/off/no disables; default on),
``DYNAMO_TPU_TIMELINE_RECORDS`` (ring depth; 0 keeps the streaming
digests but drops the exact-interval ring; unset = 256).
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

log = logging.getLogger("dynamo_tpu.timeline")

DEFAULT_CAPACITY = 256
CAPACITY_ENV = "DYNAMO_TPU_TIMELINE_RECORDS"
ENABLE_ENV = "DYNAMO_TPU_TIMELINE"

# instrumented phase names, in pipeline order
PHASES = ("admit", "page_alloc", "dispatch", "device_wait", "detok", "bank")
# phases during which the DEVICE is (or may be) busy on our behalf; the
# rest are pure host work — the candidates that "eat" the dispatch gap
DEVICE_PHASES = frozenset(("dispatch", "device_wait"))


def _env_capacity() -> int:
    raw = os.environ.get(CAPACITY_ENV, "")
    try:
        return int(raw) if raw.strip() else DEFAULT_CAPACITY
    except ValueError:
        log.warning("bad %s=%r; using default %d", CAPACITY_ENV, raw,
                    DEFAULT_CAPACITY)
        return DEFAULT_CAPACITY


def _env_enabled() -> bool:
    raw = os.environ.get(ENABLE_ENV, "").strip().lower()
    return raw not in ("0", "false", "off", "no")


class PhaseDigest:
    """Streaming duration histogram: quarter-octave log buckets
    0.25ms..~8.2s — the engine PhaseTimer's exact bucket scheme, so the
    exposition bridge serves both under one
    ``dynamo_engine_phase_seconds`` series without a second edge set."""

    _EDGES_MS = [0.25 * 2 ** (i / 4) for i in range(61)]  # 0.25ms .. ~8.2s

    __slots__ = ("count", "sum_s", "buckets")

    def __init__(self):
        self.count = 0
        self.sum_s = 0.0
        self.buckets = [0] * (len(self._EDGES_MS) + 1)

    def observe(self, seconds: float) -> None:
        self.count += 1
        self.sum_s += seconds
        ms = seconds * 1e3
        lo, hi = 0, len(self._EDGES_MS)
        while lo < hi:  # first edge >= ms (binary search; 61 edges)
            mid = (lo + hi) // 2
            if ms <= self._EDGES_MS[mid]:
                hi = mid
            else:
                lo = mid + 1
        self.buckets[lo] += 1

    def quantile_ms(self, q: float) -> float:
        """Geometric-midpoint estimate of the q-quantile (PhaseTimer's
        scheme; worst-case error ~9%)."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        seen = 0
        for i, n in enumerate(self.buckets):
            seen += n
            if seen >= target:
                if i >= len(self._EDGES_MS):
                    return self._EDGES_MS[-1]
                hi = self._EDGES_MS[i]
                lo_edge = self._EDGES_MS[i - 1] if i > 0 else hi / 2 ** 0.25
                return (lo_edge * hi) ** 0.5
        return self._EDGES_MS[-1]


class _Phase:
    """Reusable-shape context manager for one instrumented phase; kept
    allocation-light because several open per engine step."""

    __slots__ = ("_tl", "_name", "_watched")

    def __init__(self, tl: "StepTimeline", name: str):
        self._tl = tl
        self._name = name
        self._watched = False

    def __enter__(self) -> "_Phase":
        # device seams feed the engine watchdog even when the timeline
        # draft is closed (disabled timeline, disagg prefill outside
        # step()) — hang detection must not depend on record keeping
        watch = self._tl.watch
        if watch is not None and self._name in DEVICE_PHASES:
            self._watched = watch
            watch.device_enter(self._name)
        self._tl._enter(self._name)
        return self

    def __exit__(self, *exc) -> bool:
        self._tl._exit()
        watch, self._watched = self._watched, False
        if watch:
            watch.device_exit(self._name)
        return False


class StepTimeline:
    """Bounded ring of exact per-step phase intervals + streaming
    per-phase digests + inter-dispatch host-gap accounting."""

    def __init__(self, capacity: Optional[int] = None,
                 enabled: Optional[bool] = None):
        if capacity is None:
            capacity = _env_capacity()
        if enabled is None:
            enabled = _env_enabled()
        self.capacity = max(0, int(capacity))
        self.enabled = bool(enabled)
        self._ring: "collections.deque[Dict[str, Any]]" = collections.deque(  # guarded_by: _lock
            maxlen=max(1, self.capacity))
        self._lock = threading.Lock()
        self._seq = 0  # guarded_by: _lock — monotonic id, survives wrap
        self.steps_total = 0
        self.dropped_total = 0
        # lifetime streaming digests (scheduler-thread writes; scrape
        # reads are monotonic-safe the same way PhaseTimer's are)
        self.digests: Dict[str, PhaseDigest] = {p: PhaseDigest()
                                                for p in PHASES}
        self.gap_digest = PhaseDigest()  # inter-dispatch host-gap samples
        self.phase_totals: Dict[str, float] = {p: 0.0 for p in PHASES}
        self.host_gap_total_s = 0.0
        self.wall_total_s = 0.0
        # open per-step draft + phase stack; engine scheduler thread only
        self._draft: Optional[Dict[str, Any]] = None
        self._stack: List[List[Any]] = []  # [name, segment_open_monotonic]
        self._last_return: Optional[float] = None  # device ctrl-return mark
        # optional EngineWatchdog: device-phase enter/exit mirror — hang
        # detection coverage tracks stepline instrumentation exactly
        self.watch: Optional[Any] = None

    # ------------------------------------------------------ engine thread --
    def reset(self) -> None:
        """Zero the streaming digests and drop the ring (engine
        reset_metrics: post-warmup / bench phase boundaries, so bubble
        baselines exclude compile-time outliers).  Any open draft is
        discarded; `seq` keeps counting so record ids stay unique."""
        with self._lock:
            self._ring.clear()
        self.steps_total = 0
        self.dropped_total = 0
        self.digests = {p: PhaseDigest() for p in PHASES}
        self.gap_digest = PhaseDigest()
        self.phase_totals = {p: 0.0 for p in PHASES}
        self.host_gap_total_s = 0.0
        self.wall_total_s = 0.0
        self._draft = None
        self._stack = []
        self._last_return = None

    def begin_step(self) -> None:
        """Open the draft for one `Engine.step()`.  A draft still open
        from a previous begin means that step unwound past commit
        (exception): finalize what it measured, flagged, never lose it."""
        if not self.enabled:
            return
        if self._draft is not None:
            self._finalize(aborted=True)
        self._draft = {"t0": time.monotonic(), "t0_unix_ns": time.time_ns(),
                       "segs": [], "gaps": []}
        self._stack = []

    def phase(self, name: str) -> _Phase:
        """Context manager for one instrumented phase of the open step.
        No-op outside an open draft (disabled timeline, or engine paths
        like the disagg prefill role that run outside step())."""
        return _Phase(self, name)

    def _enter(self, name: str) -> None:
        d = self._draft
        if d is None:
            return
        now = time.monotonic()
        stack = self._stack
        if stack:
            # nested phase: PAUSE the outer one — close its open segment
            # so recorded intervals are exclusive self-time, disjoint by
            # construction (the conservation invariant rests on this)
            outer = stack[-1]
            if now > outer[1]:
                d["segs"].append((outer[0], outer[1] - d["t0"],
                                  now - d["t0"]))
        if name == "dispatch" and self._last_return is not None:
            # inter-dispatch host gap: device program N returned control
            # at _last_return; program N+1 launches now. Clamped — async
            # scheduling dispatches N+1 before materializing N.
            d["gaps"].append(max(0.0, now - self._last_return))
        stack.append([name, now])

    def _exit(self) -> None:
        d = self._draft
        stack = self._stack
        if d is None or not stack:
            return
        now = time.monotonic()
        top = stack.pop()
        if now > top[1]:
            d["segs"].append((top[0], top[1] - d["t0"], now - d["t0"]))
        if top[0] in DEVICE_PHASES:
            self._last_return = now
        if stack:
            stack[-1][1] = now  # resume the paused outer phase

    def commit_step(self, **fields: Any) -> None:
        """Finalize the open step record.  Steps that measured nothing
        (no phase ran) are dropped — an idle engine tick must not wash
        real history out of the ring."""
        if not self.enabled:
            return
        self._finalize(aborted=False, **fields)

    def _finalize(self, aborted: bool, **fields: Any) -> None:
        d, self._draft = self._draft, None
        if d is None:
            return
        now = time.monotonic()
        # an exception may unwind past open phases: close them newest-
        # first so the segments stay disjoint
        while self._stack:
            top = self._stack.pop()
            if now > top[1]:
                d["segs"].append((top[0], top[1] - d["t0"], now - d["t0"]))
            if self._stack:
                self._stack[-1][1] = now
        if not d["segs"]:
            return
        wall = now - d["t0"]
        sums: Dict[str, float] = {}
        for name, s0, s1 in d["segs"]:
            sums[name] = sums.get(name, 0.0) + (s1 - s0)
        # conservation residue: host time inside the step no instrumented
        # phase claimed (>= 0 by construction — segments are disjoint and
        # within [t0, now])
        gap = max(0.0, wall - sum(sums.values()))
        for name, tot in sums.items():
            dg = self.digests.get(name)
            if dg is not None:
                dg.observe(tot)
                self.phase_totals[name] += tot
        for g in d["gaps"]:
            self.gap_digest.observe(g)
            self.host_gap_total_s += g
        self.wall_total_s += wall
        self.steps_total += 1
        rec: Dict[str, Any] = {
            "t0_unix_ns": d["t0_unix_ns"],
            "wall_s": wall,
            "phases": {k: round(v, 9) for k, v in sums.items()},
            "segs": [(n, round(s0, 9), round(s1, 9))
                     for n, s0, s1 in d["segs"]],
            "gap_s": gap,
            "host_gap": [round(g, 9) for g in d["gaps"]],
        }
        if aborted:
            rec["aborted"] = True
        rec.update(fields)
        if self.capacity > 0:
            self._append(rec)

    # --------------------------------------------------------- internals ---
    def _append(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            rec["seq"] = self._seq
            self._seq += 1
            if len(self._ring) == self._ring.maxlen:
                self.dropped_total += 1
            self._ring.append(rec)

    def records(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._ring)
        if n is not None and n > 0:
            out = out[-n:]
        return out

    # ----------------------------------------------------------- summary ---
    def summary(self) -> Dict[str, Any]:
        """Bubble-attribution rollup: per-phase p50/p95 + share of step
        wall time, the inter-dispatch host-gap distribution, and which
        host phase eats the gap.  Rides /worker/stats and the heartbeat
        (fleet rollup via merge_summaries)."""
        wall = self.wall_total_s
        phases: Dict[str, Any] = {}
        for name in PHASES:
            dg = self.digests[name]
            if not dg.count:
                continue
            phases[name] = {
                "count": dg.count,
                "total_s": round(self.phase_totals[name], 6),
                "p50_ms": round(dg.quantile_ms(0.5), 3),
                "p95_ms": round(dg.quantile_ms(0.95), 3),
                "share": round(self.phase_totals[name] / wall, 4)
                if wall else 0.0,
            }
        tracked = sum(self.phase_totals.values())
        gd = self.gap_digest
        out: Dict[str, Any] = {
            "enabled": self.enabled,
            "steps": self.steps_total,
            "wall_s": round(wall, 6),
            "phases": phases,
            "host_gap": {
                "count": gd.count,
                "total_s": round(self.host_gap_total_s, 6),
                "p50_ms": round(gd.quantile_ms(0.5), 3),
                "p95_ms": round(gd.quantile_ms(0.95), 3),
                "share": round(self.host_gap_total_s / wall, 4)
                if wall else 0.0,
            },
            "untracked_s": round(max(0.0, wall - tracked), 6),
        }
        bubble = _bubble_attribution(
            {n: self.phase_totals[n] for n in PHASES},
            max(0.0, wall - tracked), wall)
        if bubble is not None:
            out["bubble"] = bubble
        return out


def _bubble_attribution(phase_totals: Dict[str, float], untracked: float,
                        wall: float) -> Optional[Dict[str, Any]]:
    """Which HOST phase eats the inter-dispatch gap: rank the non-device
    phases (plus the untracked residue) by their share of step wall."""
    eaters = {n: t for n, t in phase_totals.items()
              if n not in DEVICE_PHASES and t > 0}
    if untracked > 0:
        eaters["untracked"] = untracked
    if not eaters or wall <= 0:
        return None
    ranked = sorted(eaters.items(), key=lambda kv: -kv[1])
    return {
        "gap_eater": ranked[0][0],
        "host_shares": {n: round(t / wall, 4) for n, t in ranked},
    }


def merge_summaries(summaries: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fleet-wide rollup of per-worker `summary()` payloads (heartbeat
    aggregation on the frontend).  Totals and shares merge exactly;
    quantiles don't survive summarization, so the merged view reports
    worst-worker p95 per phase instead."""
    agg: Dict[str, Any] = {
        "steps": 0, "wall_s": 0.0, "untracked_s": 0.0,
        "phases": {},
        "host_gap": {"count": 0, "total_s": 0.0, "p95_ms_max": 0.0},
    }
    for s in summaries:
        if not s:
            continue
        agg["steps"] += s.get("steps", 0)
        agg["wall_s"] += s.get("wall_s", 0.0)
        agg["untracked_s"] += s.get("untracked_s", 0.0)
        hg = s.get("host_gap") or {}
        agg["host_gap"]["count"] += hg.get("count", 0)
        agg["host_gap"]["total_s"] += hg.get("total_s", 0.0)
        agg["host_gap"]["p95_ms_max"] = max(
            agg["host_gap"]["p95_ms_max"], hg.get("p95_ms", 0.0))
        for name, ph in (s.get("phases") or {}).items():
            t = agg["phases"].setdefault(
                name, {"count": 0, "total_s": 0.0, "p95_ms_max": 0.0})
            t["count"] += ph.get("count", 0)
            t["total_s"] += ph.get("total_s", 0.0)
            t["p95_ms_max"] = max(t["p95_ms_max"], ph.get("p95_ms", 0.0))
    wall = agg["wall_s"]
    if wall > 0:
        for ph in agg["phases"].values():
            ph["share"] = round(ph["total_s"] / wall, 4)
        agg["host_gap"]["share"] = round(
            agg["host_gap"]["total_s"] / wall, 4)
    agg["wall_s"] = round(agg["wall_s"], 6)
    agg["untracked_s"] = round(agg["untracked_s"], 6)
    agg["host_gap"]["total_s"] = round(agg["host_gap"]["total_s"], 6)
    for ph in agg["phases"].values():
        ph["total_s"] = round(ph["total_s"], 6)
    bubble = _bubble_attribution(
        {n: p["total_s"] for n, p in agg["phases"].items()},
        agg["untracked_s"], wall)
    if bubble is not None:
        agg["bubble"] = bubble
    return agg


# ------------------------------------------------------- Perfetto export ---

_ENGINE_PID = 1
_SPAN_PID = 2


def _arg_value(v: Any) -> Any:
    return v if isinstance(v, (str, int, float, bool)) or v is None \
        else str(v)


def perfetto_trace(timeline: "StepTimeline", collector=None,
                   steps: int = 128,
                   trace_id: Optional[str] = None) -> Dict[str, Any]:
    """Chrome Trace Event JSON (the array format Perfetto/chrome://tracing
    ingest): engine step phases + step-boundary markers on one track,
    request spans on per-service tracks, all on the unix-epoch clock in
    microseconds — step records anchor ``time.time_ns`` at begin, and
    tracing spans are ``time_ns`` natively, so a request's spans line up
    with the engine steps that served it."""
    events: List[Dict[str, Any]] = [
        {"name": "process_name", "ph": "M", "pid": _ENGINE_PID,
         "args": {"name": "engine"}},
        {"name": "thread_name", "ph": "M", "pid": _ENGINE_PID, "tid": 1,
         "args": {"name": "engine.step"}},
    ]
    for rec in timeline.records(steps):
        base_us = rec["t0_unix_ns"] / 1e3
        events.append({
            "name": "step", "ph": "i", "s": "t", "cat": "engine",
            "ts": round(base_us, 3), "pid": _ENGINE_PID, "tid": 1,
            "args": {"seq": rec.get("seq"),
                     "wall_ms": round(rec["wall_s"] * 1e3, 3),
                     "gap_ms": round(rec["gap_s"] * 1e3, 3),
                     "host_gap_ms": [round(g * 1e3, 3)
                                     for g in rec.get("host_gap", [])]},
        })
        for name, s0, s1 in rec["segs"]:
            events.append({
                "name": name, "ph": "X", "cat": "engine",
                "ts": round(base_us + s0 * 1e6, 3),
                "dur": round((s1 - s0) * 1e6, 3),
                "pid": _ENGINE_PID, "tid": 1,
                "args": {"step": rec.get("seq")},
            })
    if collector is not None:
        tids: Dict[str, int] = {}
        for sp in collector.snapshot(trace_id=trace_id):
            if sp.end_ns is None:
                continue
            tid = tids.setdefault(sp.service, len(tids) + 1)
            events.append({
                "name": sp.name, "ph": "X", "cat": "request",
                "ts": round(sp.start_ns / 1e3, 3),
                "dur": round((sp.end_ns - sp.start_ns) / 1e3, 3),
                "pid": _SPAN_PID, "tid": tid,
                "args": {"trace_id": sp.trace_id, "span_id": sp.span_id,
                         **{k: _arg_value(v)
                            for k, v in sp.attributes.items()}},
            })
        if tids:
            events.append({"name": "process_name", "ph": "M",
                           "pid": _SPAN_PID, "args": {"name": "requests"}})
            for service, tid in tids.items():
                events.append({"name": "thread_name", "ph": "M",
                               "pid": _SPAN_PID, "tid": tid,
                               "args": {"name": service}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def timeline_debug_payload(timeline: "StepTimeline",
                           qs: Dict[str, List[str]],
                           collector=None) -> Dict[str, Any]:
    """Build the `GET /debug/timeline` response from parsed query params.

    ``steps`` bounds the records considered (default 128);
    ``format=perfetto`` emits Chrome Trace Event JSON (optionally
    filtered to one request via ``trace_id=``), ``format=summary`` the
    bubble-attribution rollup, anything else the raw interval records."""
    def one(key: str) -> Optional[str]:
        vals = qs.get(key) or []
        return vals[0] if vals and vals[0] != "" else None

    try:
        n = int(one("steps") or 128)
    except ValueError:
        n = 128
    fmt = (one("format") or "json").lower()
    if fmt == "perfetto":
        return perfetto_trace(timeline, collector, steps=n,
                              trace_id=one("trace_id"))
    if fmt == "summary":
        return timeline.summary()
    return {
        "enabled": timeline.enabled,
        "capacity": timeline.capacity,
        "size": len(timeline.records()),
        "steps_total": timeline.steps_total,
        "dropped_total": timeline.dropped_total,
        "records": timeline.records(n),
        "summary": timeline.summary(),
    }
