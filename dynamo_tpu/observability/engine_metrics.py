"""Engine-phase exposition: bridge in-engine timings onto worker /metrics.

The engine's PhaseTimer histograms (engine.EngineMetrics — per-phase
step-time distributions recorded always-on in the hot loop) were only
visible via `/worker/stats` JSON; this module serves them as real
Prometheus series so Grafana/alerting see per-phase latency without a
second observation path:

- `dynamo_engine_phase_seconds{phase}` — prefill / prefill_chunk /
  decode_window / decode_step / mixed_step histograms (PhaseTimer's
  quarter-octave buckets downsampled to octaves: 0.25ms..8.2s, 16 edges),
  plus the step-timeline self-time phases (admit / page_alloc / dispatch /
  device_wait / detok / bank) from observability/timeline.py riding the
  same series as additional label values;
- `dynamo_engine_host_gap_seconds` — inter-dispatch host gap sampled by
  the step timeline at every device-program launch (the zero-bubble
  roadmap item's acceptance number);
- `dynamo_engine_batch_occupancy` — decode-window batch occupancy
  (active slots / max_num_seqs) histogram;
- `dynamo_engine_mixed_prefill_fraction` — unified ragged step
  composition: the prefill-token fraction of each mixed window's rows
  (docs/perf.md "Unified ragged step"; persistently high fractions mean
  --mixed-batch-tokens crowds decode, near-zero means the budget is
  slack);
- `dynamo_engine_spec_draft_tokens_total{drafter}` /
  `dynamo_engine_spec_accepted_tokens_total{drafter}` /
  `dynamo_engine_spec_accept_length{drafter}` — speculative decoding
  health, one series per drafter (ngram | model) so the proposers'
  acceptance is separable on one scrape: accepted/draft is the live
  acceptance rate, and the acceptance-length histogram (0..K integer
  buckets) shows whether --num-speculative-tokens is tuned to the
  workload (docs/perf.md "Speculative decoding v2" / "Speculation v3");
- `dynamo_pallas_fallback_total{op,reason}` — Pallas→XLA demotions the
  head/lane gates (and int8 lane-blocking / seq-parallel mesh checks)
  made silently before; each label pair also logs one warning at first
  occurrence (ops/attention._note_fallback);
- `dynamo_engine_jit_programs` — compiled executables across the jit
  caches (steady-state growth = recompiles, the thing the bucketed
  shapes exist to prevent) + `dynamo_engine_warmup_seconds`;
- `dynamo_engine_mfu` / `dynamo_engine_mbu` — LIVE roofline utilization:
  decode token throughput over the scrape window against the chip's
  datasheet peaks, the same formulas bench.py reports offline
  (profiler/roofline.py). The chip is identified from the jax device
  (profiler.systems.chip_for_device_kind) or forced with
  `DYNAMO_TPU_CHIP=v5e|v5p|v6e|v4`; with no identifiable chip (CPU
  fallback) both gauges read 0 — never a fabricated utilization.

Everything reads engine counters at scrape time; nothing new rides the
decode loop.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

from dynamo_tpu.serving.metrics import (
    CallbackCounter,
    CallbackCounterVec,
    CallbackHistogram,
    Gauge,
    Registry,
)

log = logging.getLogger("dynamo_tpu.engine_metrics")

# downsample PhaseTimer's 61 quarter-octave edges to octaves: every 4th
# edge, 0.25ms..8.2s — 16 buckets per phase keeps the scrape compact while
# preserving ~2x quantile resolution
_OCTAVE_STRIDE = 4


def _downsample_cum(buckets, raw_count, idxs):
    """Cumulative octave buckets from a quarter-octave histogram (shared
    by PhaseTimer and timeline.PhaseDigest — same edge scheme)."""
    cum = []
    running = 0
    j = 0
    for i in idxs:
        while j <= i:
            running += buckets[j]
            j += 1
        cum.append(running)
    # single count read AFTER the bucket reads, used for both the
    # +Inf bucket and _count: a concurrent observe can only make the
    # tail larger, never break +Inf == _count or monotonicity
    count = max(raw_count, running)
    cum.append(count)  # +Inf
    return cum, count


def _phase_series(engine):
    from dynamo_tpu.engine.engine import PhaseTimer

    edges_ms = PhaseTimer._EDGES_MS
    idxs = list(range(0, len(edges_ms), _OCTAVE_STRIDE))
    edges_s = [round(edges_ms[i] / 1e3, 8) for i in idxs]
    out = []
    for phase, timer in engine.metrics.phases.items():
        cum, count = _downsample_cum(timer.buckets, timer.count, idxs)
        out.append(({"phase": phase}, edges_s, cum,
                    round(timer.sum_s, 6), count))
    # step-timeline phase digests (admit/page_alloc/dispatch/device_wait/
    # detok/bank) ride the same series as additional `phase` label values:
    # PhaseDigest replicates PhaseTimer's bucket scheme by construction,
    # and the two name sets are disjoint
    for phase, dg in engine.timeline.digests.items():
        if not dg.count:
            continue
        cum, count = _downsample_cum(dg.buckets, dg.count, idxs)
        out.append(({"phase": phase}, edges_s, cum,
                    round(dg.sum_s, 6), count))
    return out


def _host_gap_series(engine):
    """Inter-dispatch host-gap distribution from the step timeline — the
    zero-bubble roadmap item's acceptance number."""
    from dynamo_tpu.observability.timeline import PhaseDigest

    edges_ms = PhaseDigest._EDGES_MS
    idxs = list(range(0, len(edges_ms), _OCTAVE_STRIDE))
    edges_s = [round(edges_ms[i] / 1e3, 8) for i in idxs]
    gd = engine.timeline.gap_digest
    cum, count = _downsample_cum(gd.buckets, gd.count, idxs)
    return [({}, edges_s, cum, round(gd.sum_s, 6), count)]


def _occupancy_series(engine):
    m = engine.metrics
    edges = list(m._OCC_EDGES)
    cum = []
    running = 0
    for c in m.occupancy_buckets[:-1]:
        running += c
        cum.append(running)
    # derived total serves as BOTH +Inf and _count (observe_occupancy
    # bumps buckets before count, so the two fields could disagree for a
    # concurrent scrape if read separately)
    total = running + m.occupancy_buckets[-1]
    cum.append(total)  # +Inf
    return [({}, edges, cum, round(m.occupancy_sum, 6), total)]


def _mixed_series(engine):
    """Ragged-batch composition (EngineMetrics.observe_mixed): prefill-
    token fraction per unified mixed window, same cumulative-bucket
    scheme as occupancy."""
    m = engine.metrics
    edges = list(m._OCC_EDGES)
    cum = []
    running = 0
    for c in m.mixed_buckets[:-1]:
        running += c
        cum.append(running)
    total = running + m.mixed_buckets[-1]
    cum.append(total)  # +Inf
    return [({}, edges, cum, round(m.mixed_sum, 6), total)]


def _spec_series(engine):
    """Speculative acceptance length per verify window
    (EngineMetrics.observe_spec_accept): how many of the K drafted tokens
    the target chain accepted, integer edges 0..K, one labeled series per
    drafter (ngram | model) so the n-gram vs draft-model histograms are
    separable on one scrape. Same cumulative-bucket scheme as occupancy;
    mean acceptance = _sum / _count. No observations yet -> no series (a
    phantom unlabeled sample would break the drafter split)."""
    m = engine.metrics
    edges = list(m._SPEC_EDGES)
    out = []
    for drafter, buckets in sorted(m.spec_hist_by.items()):
        cum = []
        running = 0
        for c in buckets[:-1]:
            running += c
            cum.append(running)
        total = running + buckets[-1]
        cum.append(total)  # +Inf
        out.append(({"drafter": drafter}, edges, cum,
                    float(m.spec_sum_by.get(drafter, 0)), total))
    return out


def _fallback_counts():
    """dynamo_pallas_fallback_total labels from the attention dispatch's
    demotion bookkeeping (process-wide; each pair warned once)."""
    from dynamo_tpu.ops import attention as att

    return {(("op", op), ("reason", reason)): v
            for (op, reason), v in att.pallas_fallback_counts().items()}


def resolve_chip():
    """The chip spec live utilization is judged against: env override
    first (`DYNAMO_TPU_CHIP`), else the jax device kind."""
    from dynamo_tpu.profiler import systems

    forced = os.environ.get("DYNAMO_TPU_CHIP")
    if forced:
        chip = systems.CHIPS.get(forced.strip().lower())
        if chip is not None:
            return chip
        log.warning("unknown DYNAMO_TPU_CHIP=%r (known: %s)", forced,
                    sorted(systems.CHIPS))
    try:
        import jax

        kind = getattr(jax.devices()[0], "device_kind", "")
    except Exception:
        return None
    return systems.chip_for_device_kind(kind)


class EngineMetricsBridge:
    """Registers the dynamo_engine_* series against a worker registry and
    refreshes the MFU/MBU gauges at scrape time."""

    def __init__(self, registry: Registry, engine, clock=time.monotonic):
        self.engine = engine
        self.clock = clock
        self.chip = resolve_chip()
        CallbackHistogram(
            "dynamo_engine_phase_seconds",
            "Engine phase step-time distribution (PhaseTimer bridge)",
            registry, lambda: _phase_series(self.engine))
        CallbackHistogram(
            "dynamo_engine_host_gap_seconds",
            "Inter-dispatch host gap: wall time between a device program "
            "returning control and the next program launching (step "
            "timeline; the zero-bubble target)",
            registry, lambda: _host_gap_series(self.engine))
        CallbackHistogram(
            "dynamo_engine_batch_occupancy",
            "Decode-window batch occupancy (active slots / max_num_seqs)",
            registry, lambda: _occupancy_series(self.engine))
        CallbackHistogram(
            "dynamo_engine_mixed_prefill_fraction",
            "Unified ragged step composition: prefill-token fraction of "
            "each mixed window's rows",
            registry, lambda: _mixed_series(self.engine))
        CallbackHistogram(
            "dynamo_engine_spec_accept_length",
            "Accepted draft tokens per speculative verify window (0..K), "
            "per drafter (ngram | model); mean acceptance length = "
            "_sum / _count",
            registry, lambda: _spec_series(self.engine))
        CallbackCounterVec(
            "dynamo_engine_spec_draft_tokens_total",
            "Draft tokens proposed to speculative verify windows, per "
            "drafter (ngram | model)",
            registry, lambda: {(("drafter", d),): v for d, v in
                               self.engine.metrics.spec_draft_by.items()},
            labelnames=("drafter",))
        CallbackCounterVec(
            "dynamo_engine_spec_accepted_tokens_total",
            "Draft tokens the target chain accepted, per drafter "
            "(acceptance rate = accepted / draft)",
            registry, lambda: {(("drafter", d),): v for d, v in
                               self.engine.metrics.spec_accepted_by.items()},
            labelnames=("drafter",))
        CallbackCounterVec(
            "dynamo_pallas_fallback_total",
            "Pallas kernels demoted to the XLA path by the head/lane "
            "gates, int8 lane-blocking, or a sequence-parallel mesh "
            "(each op/reason pair also warns once at first occurrence)",
            registry, _fallback_counts, labelnames=("op", "reason"))
        CallbackCounter(
            "dynamo_engine_jit_programs",
            "Compiled executables across the engine's jit caches "
            "(growth after warmup = steady-state recompiles)",
            registry, self._program_count)
        self.warmup_gauge = Gauge(
            "dynamo_engine_warmup_seconds",
            "Wall time the AOT warmup spent compiling before /ready",
            registry)
        self.mfu_gauge = Gauge(
            "dynamo_engine_mfu",
            "Model FLOPs utilization of the decode phase over the scrape "
            "window (vs datasheet peak; 0 when no chip is identified)",
            registry)
        self.mbu_gauge = Gauge(
            "dynamo_engine_mbu",
            "Model bandwidth utilization of the decode phase over the "
            "scrape window (weights + KV stream vs datasheet HBM bw)",
            registry)
        # utilization deltas: (output_tokens, decode_time_s, decode_steps)
        self._prev = (0, 0.0, 0)

    def _program_count(self) -> int:
        try:
            return self.engine.compiled_program_count()
        except Exception:
            return 0

    # ---------------------------------------------------------- refresh ----
    def refresh(self) -> None:
        """Scrape-time update of the warmup + MFU/MBU gauges. Utilization
        covers decode activity since the PREVIOUS scrape, measured against
        decode-busy time (kernel efficiency — independent of idle gaps)."""
        eng = self.engine
        info = getattr(eng, "warmup_info", None)
        if info:
            self.warmup_gauge.set(float(info.get("seconds", 0.0)))
        m = eng.metrics
        cur = (m.output_tokens, m.decode_time_s, m.decode_steps)
        prev, self._prev = self._prev, cur
        d_tok = cur[0] - prev[0]
        d_time = cur[1] - prev[1]
        d_steps = cur[2] - prev[2]
        if d_tok <= 0 or d_time <= 0 or d_steps <= 0:
            # reset_metrics() (bench boundaries) or an idle window: report
            # zero utilization rather than a stale or negative number
            self.mfu_gauge.set(0.0)
            self.mbu_gauge.set(0.0)
            return
        mfu, mbu = self._utilization(d_tok, d_time, d_steps)
        self.mfu_gauge.set(mfu)
        self.mbu_gauge.set(mbu)

    def _utilization(self, d_tok: int, d_time: float, d_steps: int):
        if self.chip is None:
            return 0.0, 0.0
        from dynamo_tpu.profiler import roofline

        eng = self.engine
        cfg, mcfg = eng.cfg, eng.model_cfg
        tok_s = d_tok / d_time
        # mean live batch over the window: tokens emitted per decode step
        batch = max(d_tok / d_steps, 1.0)
        # mean context length of the live batch (roofline KV-stream term);
        # an empty engine at scrape time falls back to half the max context
        seqs = list(eng.seqs.values())
        avg_ctx = (sum(s.num_tokens for s in seqs) / len(seqs)
                   if seqs else cfg.max_seq_len / 2.0)
        tp = max(cfg.tensor_parallel, 1)
        wb = roofline.weight_bytes(cfg.quantization)
        kvb = roofline.kv_bytes_per_token(mcfg, cfg.kv_cache_dtype, tp=tp)
        active = roofline.active_param_count(mcfg)
        stream = (roofline.param_count(mcfg) * wb / tp
                  + batch * kvb * avg_ctx)
        mfu = tok_s * 2.0 * active / (tp * self.chip.bf16_flops)
        mbu = (tok_s / batch) * stream / (tp * self.chip.hbm_bw)
        # 4 significant digits, not 4 decimals: a tiny debug model on CPU
        # legitimately runs at ~1e-7 utilization and must not read as 0
        return float(f"{mfu:.4g}"), float(f"{mbu:.4g}")


def attach_engine_metrics(registry: Registry, engine) -> EngineMetricsBridge:
    return EngineMetricsBridge(registry, engine)
