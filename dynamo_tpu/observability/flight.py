"""Engine flight recorder: a bounded ring of structured per-step records.

The engine loop is a single-writer system — `Engine.step()` runs under
`_exec_lock` on one scheduler thread — so the recorder exploits that:
the engine opens a *draft* record at the top of each step, every decision
taken during the step (`admit`, `defer`, `qos_preempt` victim+beneficiary,
`spec_demote`, `kvbm_demote`/`kvbm_onboard`, `kv_oom`, `preempt`,
`finish`, …) attaches to the open draft lock-free, and the draft commits
into the ring with the step's batch composition and phase timings at the
end.  The only lock is a tiny mutex around ring append/snapshot; producer
threads (HTTP handlers noting a `resume` seam, aborts) that fire while no
draft is open commit standalone event records through the same mutex.

Exposure:

- ``GET /debug/flight?n=&rid=&tenant=&kind=&class=`` on every worker
  (`debug_flight_payload`) — filterable, newest-last;
- ``dump(reason)`` — the crash/abort hook: flushes any open draft (the
  partially-executed step that died is exactly the forensic record you
  want), appends a dump marker, and logs the ring tail so the history
  survives even if the process exits before anyone scrapes it.

Ring capacity comes from ``DYNAMO_TPU_FLIGHT_RECORDS`` (default 512;
0 disables recording entirely — every hook degrades to a no-op).
"""

from __future__ import annotations

import collections
import logging
import os
import threading
import time
from typing import Any, Dict, List, Optional

log = logging.getLogger("dynamo_tpu.flight")

DEFAULT_CAPACITY = 512
CAPACITY_ENV = "DYNAMO_TPU_FLIGHT_RECORDS"
# how many trailing records a dump writes to the log (full ring goes to
# the returned payload; the log line is for post-mortem grep)
DUMP_LOG_TAIL = 8


def _env_capacity() -> int:
    raw = os.environ.get(CAPACITY_ENV, "")
    try:
        return int(raw) if raw.strip() else DEFAULT_CAPACITY
    except ValueError:
        log.warning("bad %s=%r; using default %d", CAPACITY_ENV, raw,
                    DEFAULT_CAPACITY)
        return DEFAULT_CAPACITY


class FlightRecorder:
    """Bounded, lock-cheap ring of per-step engine records."""

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = _env_capacity()
        self.capacity = max(0, int(capacity))
        self.enabled = self.capacity > 0
        self._ring: "collections.deque[Dict[str, Any]]" = collections.deque(  # guarded_by: _lock
            maxlen=max(1, self.capacity))
        self._lock = threading.Lock()
        self._seq = 0  # guarded_by: _lock — monotonic id, survives wrap
        self.steps_total = 0
        self.dropped_total = 0
        # open per-step draft; engine scheduler thread only (begin/commit)
        self._draft: Optional[Dict[str, Any]] = None

    # ------------------------------------------------------ engine thread --
    def begin(self) -> None:
        """Open the draft for one `Engine.step()`.  A draft still open from
        a previous begin means that step died mid-flight (exception unwound
        past commit): flush it flagged, never lose it."""
        if not self.enabled:
            return
        stale = self._draft
        if stale is not None:
            stale["aborted"] = True
            self._close_draft(stale, "aborted")
            self._append(stale)
        self._draft = {"t": time.time(), "kinds": [], "phases": {},
                       "events": []}

    def phase(self, kind: str, dur_s: float, **fields: Any) -> None:
        """Record one executed segment (a dispatch) of the open step.
        Accumulates raw float seconds — rounding happens once at record
        flush (_close_draft), so repeated phases in one step can't
        compound per-accumulate rounding error."""
        d = self._draft
        if d is None:
            return
        d["kinds"].append(kind)
        d["phases"][kind] = d["phases"].get(kind, 0.0) + dur_s
        for k, v in fields.items():
            d[k] = v

    @staticmethod
    def _close_draft(d: Dict[str, Any], empty_kind: str) -> None:
        """Finalize a draft in place: collapse kinds and convert the
        phase accumulators to the record format (ms, 3 decimals)."""
        d["kind"] = "+".join(d.pop("kinds")) or empty_kind
        d["phases"] = {k: round(v * 1e3, 3)
                       for k, v in d["phases"].items()}

    def commit(self, **fields: Any) -> None:
        """Finalize the open step record.  Steps that did nothing (no
        segment ran, no decision fired) are dropped — an idle engine must
        not wash real history out of the ring."""
        d, self._draft = self._draft, None
        if d is None:
            return
        if not d["kinds"] and not d["events"]:
            return
        d.update(fields)
        self._close_draft(d, "event")
        self.steps_total += 1
        self._append(d)

    # ------------------------------------------------------- any thread ----
    def note(self, event: str, **fields: Any) -> None:
        """Attach a decision to the open step record, or — when no draft is
        open (producer threads: resume seams, aborts, dumps) — commit a
        standalone event record.  Appending to a live draft from a foreign
        thread is safe: list.append is atomic, and the worst race lands the
        event on the just-committed record, which is where it belongs."""
        if not self.enabled:
            return
        rec = {"ev": event}
        rec.update(fields)
        d = self._draft
        if d is not None:
            d["events"].append(rec)
        else:
            self._append({"t": time.time(), "kind": "event",
                          "events": [rec]})

    def dump(self, reason: str, **fields: Any) -> Dict[str, Any]:
        """Crash/abort dump: flush any open draft, append a dump marker,
        and log the ring tail.  Returns the full ring so callers (fatal-step
        recovery, tests) can persist or assert on it."""
        if not self.enabled:
            return {"reason": reason, "records": []}
        d, self._draft = self._draft, None
        if d is not None:
            d["aborted"] = True
            if not d["kinds"] and not d["events"]:
                d["events"].append({"ev": "empty_step"})
            self._close_draft(d, "aborted")
            self._append(d)
        self.note("dump", reason=reason, **fields)
        records = self.records()
        tail = records[-DUMP_LOG_TAIL:]
        log.error("flight dump [%s]: %d records in ring; tail: %s",
                  reason, len(records), tail)
        return {"reason": reason, **fields, "records": records}

    # --------------------------------------------------------- internals ---
    def _append(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            rec["seq"] = self._seq
            self._seq += 1
            if len(self._ring) == self._ring.maxlen:
                self.dropped_total += 1
            self._ring.append(rec)

    def records(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._ring)
        if n is not None and n > 0:
            out = out[-n:]
        return out


# ------------------------------------------------------------ filtering ----

def _matches(rec: Dict[str, Any], rid: Optional[str],
             tenant: Optional[str], kind: Optional[str],
             klass: Optional[str] = None) -> bool:
    if kind is not None and kind not in rec.get("kind", ""):
        return False

    def hit(field: str, want: str) -> bool:
        if rec.get(field) == want:
            return True
        for s in rec.get("batch", ()):
            if s.get(field) == want:
                return True
        for e in rec.get("events", ()):
            if e.get(field) == want or e.get("victim_" + field) == want \
                    or e.get("beneficiary_" + field) == want:
                return True
        return False

    if rid is not None and not hit("rid", rid):
        return False
    if tenant is not None and not hit("tenant", tenant):
        return False
    if klass is not None and not hit("class", klass):
        return False
    return True


def debug_flight_payload(recorder: FlightRecorder,
                         qs: Dict[str, List[str]]) -> Dict[str, Any]:
    """Build the `GET /debug/flight` response from parsed query params.

    ``n`` bounds the returned records (default 128, applied AFTER the
    rid/tenant/kind/class filters so a busy engine can't wash out the one
    request you're chasing).  ``class=batch`` matches records whose events
    carry ``victim_class``/``beneficiary_class`` — QoS evictions of the
    preemptible batch tier are attributable without knowing tenant ids."""
    def one(key: str) -> Optional[str]:
        vals = qs.get(key) or []
        return vals[0] if vals and vals[0] != "" else None

    try:
        n = int(one("n") or 128)
    except ValueError:
        n = 128
    rid, tenant, kind = one("rid"), one("tenant"), one("kind")
    klass = one("class")
    recs = recorder.records()
    size = len(recs)
    if rid is not None or tenant is not None or kind is not None \
            or klass is not None:
        recs = [r for r in recs if _matches(r, rid, tenant, kind, klass)]
    return {
        "enabled": recorder.enabled,
        "capacity": recorder.capacity,
        "size": size,
        "steps_total": recorder.steps_total,
        "dropped_total": recorder.dropped_total,
        "matched": len(recs),
        "records": recs[-n:] if n > 0 else recs,
    }
