"""Live memory-accounting plane: device HBM gauges + exact KV-pool books.

Two evidence classes, deliberately kept apart:

- **Runtime-reported**: `jax.local_devices()[*].memory_stats()` — real
  HBM occupancy where the backend provides it (TPU does; CPU returns
  nothing, which degrades to zero-valued gauges rather than an error).
- **Model-derived (exact)**: the KV page pool's ground truth, computed
  from `KVCacheSpec.bytes_per_token() × page_size` and the allocator's
  page books.  Every device page is attributed to exactly ONE owner —
  sequence tenant, inflight prefill, parked disagg handoff, prefix cache
  ("cache"), unattributed-but-allocated ("other"), "free", or "trash" —
  so the device-tier bytes SUM to `num_pages × page_bytes` identically
  (the conservation tests pin this).  Host/disk KVBM tiers come from the
  block pool's own books; LoRA slot residency rides along.

Exported as `dynamo_memory_*` gauges plus the `dynamo_tenant_cost_*`
counters (the engine's CostLedger read at scrape time), and as the
`memory` section of `/worker/stats`.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from dynamo_tpu.serving.metrics import (
    CallbackCounter,
    CallbackCounterVec,
    Gauge,
    Registry,
)

log = logging.getLogger("dynamo_tpu.memory")


def device_memory_stats() -> List[Dict[str, Any]]:
    """Per-device runtime memory stats; empty/zeroed where the backend
    (CPU, some emulators) doesn't report them."""
    out: List[Dict[str, Any]] = []
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return out
    for d in devices:
        try:
            ms = d.memory_stats() or {}
        except Exception:
            ms = {}
        out.append({
            "device": f"{getattr(d, 'platform', 'dev')}:{d.id}",
            "bytes_in_use": int(ms.get("bytes_in_use", 0)),
            "bytes_limit": int(ms.get("bytes_limit", 0)),
            "peak_bytes_in_use": int(ms.get("peak_bytes_in_use", 0)),
        })
    return out


class MemoryAccountant:
    """Exact, disjoint attribution of the engine's KV page pool."""

    def __init__(self, engine):
        self.engine = engine
        spec = engine.kv_spec
        self.page_bytes = spec.bytes_per_token() * spec.page_size

    # ------------------------------------------------------------ books ----
    def _page_owners(self):
        """One pass over the engine's holders: page -> (tenant, adapter).
        First claim wins (slot order, then inflight, parked, cache), so a
        cache page shared with a live sequence counts once, for the
        sequence — disjointness is what makes the sums exact."""
        eng = self.engine
        tenant_of: Dict[int, str] = {}
        adapter_of: Dict[int, str] = {}

        def claim(pages, tenant: str, adapter: str) -> None:
            for p in pages:
                if p > 0 and p not in tenant_of:
                    tenant_of[p] = tenant
                    adapter_of[p] = adapter

        for slot in sorted(list(eng.seqs)):
            seq = eng.seqs.get(slot)
            if seq is None:
                continue
            req = getattr(seq, "req", None)
            tenant = (eng._tenant_of(req) if req is not None else "default")
            adapter = (getattr(req, "adapter", None) or "base"
                       if req is not None else "base")
            claim(list(seq.pages), tenant, adapter)
        inf = getattr(eng, "_inflight", None)
        if inf is not None:
            req = getattr(inf, "req", None)
            tenant = (eng._tenant_of(req) if req is not None else "default")
            adapter = (getattr(req, "adapter", None) or "base"
                       if req is not None else "base")
            claim(list(getattr(inf, "pages", ()) or ()), tenant, adapter)
        for rid, parked in list(getattr(eng, "_parked", {}).items()):
            claim(list(parked[0]), eng._rid_tenant.get(rid, "default"),
                  "base")
        pc = getattr(eng, "prefix_cache", None)
        if pc is not None:
            for ns, pages in pc.pages_by_namespace().items():
                claim(pages, "cache", ns or "base")
        return tenant_of, adapter_of

    def snapshot(self) -> Dict[str, Any]:
        eng = self.engine
        alloc = eng.allocator
        pb = self.page_bytes
        total_pages = alloc.num_pages
        # holder iteration races the scheduler thread (same license the
        # existing /worker/stats reads run under); retry the rare
        # mutated-mid-iteration pass rather than locking the hot loop
        for attempt in range(3):
            try:
                free_pages = alloc.free_pages
                tenant_of, adapter_of = self._page_owners()
                break
            except RuntimeError:
                if attempt == 2:
                    raise
        by_tenant: Dict[str, int] = {}
        for t in tenant_of.values():
            by_tenant[t] = by_tenant.get(t, 0) + 1
        by_adapter: Dict[str, int] = {}
        for a in adapter_of.values():
            by_adapter[a] = by_adapter.get(a, 0) + 1
        claimed = len(tenant_of)
        # force the partition exact even when free_pages moved between the
        # two reads: claimed + free + other + trash == total, always
        free_pages = min(free_pages, max(0, total_pages - 1 - claimed))
        other = max(0, total_pages - 1 - free_pages - claimed)

        device_bytes = {t: n * pb for t, n in sorted(by_tenant.items())}
        if other:
            device_bytes["other"] = other * pb
        device_bytes["free"] = free_pages * pb
        device_bytes["trash"] = pb  # page 0, never allocated
        tiers: Dict[str, Dict[str, int]] = {"device": device_bytes}

        # Speculation v3: the draft model's KV pool is its own tier — a
        # first-class tenant of the memory plane with the same exact-sum
        # guarantee (the DraftEngine forces its partition the same way the
        # device tier is forced above)
        draft = getattr(eng, "draft", None)
        if draft is not None:
            tiers["draft"] = draft.partition_bytes()

        kvbm = getattr(eng, "kvbm", None)
        kvbm_stats = None
        if kvbm is not None:
            kvbm_stats = kvbm.pool.stats()
            bn = int(kvbm_stats.get("block_nbytes", 0))
            used = int(kvbm_stats.get("used_blocks", 0))
            cap = int(kvbm_stats.get("capacity_blocks", 0))
            tiers["host"] = {"cache": used * bn,
                             "free": max(0, cap - used) * bn}
            disk = kvbm_stats.get("disk")
            if disk:
                dused = int(disk.get("used_blocks", 0))
                dcap = int(disk.get("capacity_blocks", 0))
                tiers["disk"] = {"cache": dused * bn,
                                 "free": max(0, dcap - dused) * bn}

        lora = getattr(eng, "lora", None)
        lora_out: Optional[Dict[str, Any]] = None
        if lora is not None:
            resident = sorted(lora.resident())
            slots_total = int(getattr(eng.cfg, "lora_slots", 0) or 0)
            lora_out = {
                "slots_total": slots_total,
                "resident": resident,
                "slots_free": max(0, slots_total - len(resident)),
            }

        # live elasticity: the weight double-buffer ledger — staged and
        # retained-rollback trees are device bytes OUTSIDE the KV pool
        # partition (the stage budget check held headroom for them)
        wm = getattr(eng, "weights", None)
        weights_out: Optional[Dict[str, Any]] = None
        if wm is not None:
            weights_out = {
                "version": wm.version,
                "staged_version": wm.staged_version,
                "staged_bytes": wm.staged_nbytes,
                "previous_version": wm.previous_version,
                "previous_bytes": wm.previous_nbytes,
            }

        return {
            "page_bytes": pb,
            "kv_dtype": eng.kv_spec.dtype,
            "pool": {
                "total_pages": total_pages,
                "free_pages": free_pages,
                "used_pages": claimed + other,
                "trash_pages": 1,
                "total_bytes": total_pages * pb,
                "used_bytes": (claimed + other) * pb,
                "free_bytes": free_pages * pb,
            },
            "device_pages_by_tenant": dict(sorted(by_tenant.items())),
            "device_pages_by_adapter": dict(sorted(by_adapter.items())),
            "tiers": tiers,
            "kvbm": kvbm_stats,
            "lora": lora_out,
            "weights": weights_out,
            "devices": device_memory_stats(),
        }


class MemoryMetricsBridge:
    """Registers the dynamo_memory_* / dynamo_tenant_cost_* /
    dynamo_flight_* series and refreshes the gauges at scrape time."""

    def __init__(self, registry: Registry, engine):
        self.engine = engine
        self.accountant = MemoryAccountant(engine)
        self.pool_gauge = Gauge(
            "dynamo_memory_kv_pool_bytes",
            "KV cache bytes by tier (device/host/disk) and owner: tenant "
            "names plus cache/other/free/trash — each tier's samples sum "
            "to that tier's capacity (exact model-derived accounting)",
            registry, labelnames=("tier", "tenant"))
        self.pages_gauge = Gauge(
            "dynamo_memory_kv_pages",
            "Device KV page pool occupancy by state",
            registry, labelnames=("state",))
        self.device_gauge = Gauge(
            "dynamo_memory_device_bytes",
            "Runtime-reported accelerator memory (device.memory_stats(); "
            "zero on backends that do not report, e.g. CPU)",
            registry, labelnames=("device", "kind"))
        self.lora_gauge = Gauge(
            "dynamo_memory_lora_slots",
            "LoRA adapter device-slot residency",
            registry, labelnames=("state",))
        self.weights_gauge = Gauge(
            "dynamo_memory_staged_weights_bytes",
            "Weight double-buffer device bytes held by live elasticity: "
            "buffer=staged (loaded, not yet flipped) / previous (retained "
            "for rollback until commit or the next stage)",
            registry, labelnames=("buffer",))
        ledger = engine.cost
        CallbackCounterVec(
            "dynamo_tenant_cost_chip_seconds_total",
            "Per-tenant attributed engine busy time (decode slots and "
            "prefill token shares x segment wall time); tenants sum to "
            "dynamo_engine_busy_seconds_total",
            registry, lambda: {(("tenant", t),): v for t, v in
                               ledger.chip_seconds_snapshot().items()},
            labelnames=("tenant",))
        CallbackCounterVec(
            "dynamo_tenant_cost_hbm_byte_seconds_total",
            "Per-tenant KV residency cost (bytes held on device x wall "
            "time); tenants sum to dynamo_engine_hbm_byte_seconds_total",
            registry, lambda: {(("tenant", t),): v for t, v in
                               ledger.hbm_byte_seconds_snapshot().items()},
            labelnames=("tenant",))
        CallbackCounter(
            "dynamo_engine_busy_seconds_total",
            "Engine wall time attributed across tenants (conservation "
            "denominator for dynamo_tenant_cost_chip_seconds_total)",
            registry, lambda: ledger.chip_seconds_total)
        CallbackCounter(
            "dynamo_engine_hbm_byte_seconds_total",
            "KV byte-seconds attributed across tenants (conservation "
            "denominator for dynamo_tenant_cost_hbm_byte_seconds_total)",
            registry, lambda: ledger.hbm_byte_seconds_total)
        flight = engine.flight
        CallbackCounter(
            "dynamo_flight_steps_total",
            "Engine steps committed to the flight-recorder ring",
            registry, lambda: flight.steps_total)
        CallbackCounter(
            "dynamo_flight_dropped_total",
            "Flight records displaced from the bounded ring",
            registry, lambda: flight.dropped_total)
        self._pool_labels: set = set()
        self._device_labels: set = set()

    # ---------------------------------------------------------- refresh ----
    def refresh(self) -> None:
        try:
            snap = self.accountant.snapshot()
        except Exception:
            log.exception("memory snapshot failed")
            return
        live = set()
        for tier, owners in snap["tiers"].items():
            for tenant, nbytes in owners.items():
                self.pool_gauge.set(float(nbytes), tier=tier, tenant=tenant)
                live.add((tier, tenant))
        for tier, tenant in self._pool_labels - live:
            # a tenant whose last page was freed must drop to zero, not
            # freeze at its final nonzero sample
            self.pool_gauge.remove(tier=tier, tenant=tenant)
        self._pool_labels = live

        pool = snap["pool"]
        self.pages_gauge.set(float(pool["used_pages"]), state="used")
        self.pages_gauge.set(float(pool["free_pages"]), state="free")
        self.pages_gauge.set(float(pool["trash_pages"]), state="trash")

        dev_live = set()
        for d in snap["devices"]:
            for kind, key in (("in_use", "bytes_in_use"),
                              ("limit", "bytes_limit"),
                              ("peak", "peak_bytes_in_use")):
                self.device_gauge.set(float(d[key]),
                                      device=d["device"], kind=kind)
                dev_live.add((d["device"], kind))
        for device, kind in self._device_labels - dev_live:
            self.device_gauge.remove(device=device, kind=kind)
        self._device_labels = dev_live

        lora = snap.get("lora")
        if lora:
            self.lora_gauge.set(float(lora["slots_total"]), state="total")
            self.lora_gauge.set(float(len(lora["resident"])),
                                state="resident")
            self.lora_gauge.set(float(lora["slots_free"]), state="free")

        w = snap.get("weights")
        if w:
            self.weights_gauge.set(float(w["staged_bytes"]),
                                   buffer="staged")
            self.weights_gauge.set(float(w["previous_bytes"]),
                                   buffer="previous")


def attach_memory_metrics(registry: Registry, engine) -> MemoryMetricsBridge:
    return MemoryMetricsBridge(registry, engine)
