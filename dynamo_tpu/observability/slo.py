"""Declarative SLOs + multi-window burn-rate tracking.

The observability substrate planner v2 and per-tenant QoS consume
(ROADMAP: coordinated SLA autoscaling / "Taming the Chaos", arxiv
2508.19559 — disaggregated autoscaling must be driven by per-pool SLO
burn, not raw load):

- **Targets** are declarative: TTFT / ITL / error-rate objectives per
  model (full ``<base>:<adapter>`` ids address adapter SLOs) and disagg
  role, loaded from env (`DYNAMO_TPU_SLO_*`) — the operator materializes
  the manifest's ``sloTargets`` key into exactly these envs
  (operator/materialize.slo_env).
- **Burn rate** is computed FROM the existing latency histograms
  (serving/metrics.py): the engine snapshots each histogram's cumulative
  counts on every tick and banks the deltas into fixed-width time
  buckets; a window's burn rate is
  ``(breaching fraction over the window) / error budget`` where the
  budget is ``1 - goal`` for latency objectives and the allowed rate
  itself for error-rate objectives. Burn 1.0 = exactly consuming budget;
  >1.0 = the SLO is burning down. No new instrumentation rides the hot
  path.
- **Determinism**: the clock is injectable (`clock=`), so CI drives the
  whole 5m/1h window machinery with fake time (tests/test_slo.py, per
  the ROADMAP's deterministic-simulation constraint).
- **Request-rate history**: a bounded ring of per-bucket request counts
  (`GET /debug/slo?history=1`) — planner v2's traffic-forecasting input.

Exposed as `dynamo_slo_attainment` / `dynamo_slo_burn_rate` gauges
(labels: slo, objective, window, model, role) refreshed at scrape time,
plus the `GET /debug/slo` JSON endpoint on the frontend and every worker.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import os
import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from dynamo_tpu.serving.metrics import FrontendMetrics, Gauge

log = logging.getLogger("dynamo_tpu.slo")

# multi-window sliding burn rate: the fast window catches an active burn
# inside one autoscaler reaction time; the slow window filters blips
DEFAULT_WINDOWS_S = (300, 3600)
WINDOW_LABELS = {300: "5m", 3600: "1h"}
DEFAULT_BUCKET_S = 10
DEFAULT_HISTORY_BUCKETS = 360  # 1h of request-rate history at 10s buckets

TARGETS_ENV = "DYNAMO_TPU_SLO_TARGETS"
SCALAR_ENVS = {  # the one-default-target shorthand
    "DYNAMO_TPU_SLO_TTFT_MS": "ttft_ms",
    "DYNAMO_TPU_SLO_ITL_MS": "itl_ms",
    "DYNAMO_TPU_SLO_ERROR_RATE": "error_rate",
    "DYNAMO_TPU_SLO_GOAL": "goal",
}
_TARGET_KEYS = {  # accepted spec keys, camelCase (manifest) and snake_case
    "model": "model", "role": "role", "name": "name", "goal": "goal",
    "tenant": "tenant",
    "ttft_ms": "ttft_ms", "ttftMs": "ttft_ms",
    "itl_ms": "itl_ms", "itlMs": "itl_ms",
    "error_rate": "error_rate", "errorRate": "error_rate",
}


@dataclasses.dataclass(frozen=True)
class SLOTarget:
    """One declarative objective set. `model`/`role`/`tenant` are
    exact-match selectors ('*' = any); a '<base>:<adapter>' model selects
    the adapter's own latency series on the frontend. A non-wildcard
    `tenant` selects the per-tenant latency series
    (``dynamo_tenant_*``, dynamo_tpu.qos) instead of the model-labeled
    ones — the signal the QoS plane's burn-aware admission and the
    isolation chaos tests consume. Tenant selectors apply to the latency
    objectives only (there is no per-tenant error counter), so an
    error_rate on a tenant-scoped target emits no rows."""

    model: str = "*"
    role: str = "*"          # frontend | agg | prefill | decode | *
    tenant: str = "*"        # per-tenant QoS selector (dynamo_tpu.qos)
    ttft_ms: Optional[float] = None
    itl_ms: Optional[float] = None
    error_rate: Optional[float] = None
    goal: float = 0.99       # attainment objective for the latency SLOs
    name: str = ""

    def matches_model(self, model: str) -> bool:
        return self.model in ("*", model)

    def matches_role(self, role: str) -> bool:
        return self.role in ("*", role)

    @property
    def label(self) -> str:
        if self.name:
            return self.name
        parts = [p for p in (self.model, self.tenant, self.role)
                 if p != "*"]
        return "/".join(parts) or "default"

    def objectives(self) -> List[Tuple[str, float, float]]:
        """(objective, threshold, error budget) triplets. Latency budgets
        come from the attainment goal; the error-rate budget IS the target
        rate."""
        goal = min(max(self.goal, 0.0), 0.9999)
        out = []
        if self.ttft_ms is not None:
            out.append(("ttft", self.ttft_ms / 1e3, 1.0 - goal))
        if self.itl_ms is not None:
            out.append(("itl", self.itl_ms / 1e3, 1.0 - goal))
        if self.error_rate is not None and self.error_rate > 0:
            out.append(("error_rate", 0.0, self.error_rate))
        return out

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v not in (None, "")}


def target_from_dict(spec: Mapping[str, Any]) -> SLOTarget:
    """Validate one target spec (manifest or env JSON); unknown keys fail
    loudly so typos don't silently disable an SLO."""
    unknown = set(spec) - set(_TARGET_KEYS)
    if unknown:
        raise ValueError(f"unknown sloTargets keys: {sorted(unknown)}")
    kw: Dict[str, Any] = {}
    for k, v in spec.items():
        field = _TARGET_KEYS[k]
        if field in ("model", "role", "name", "tenant"):
            kw[field] = str(v)
        else:
            kw[field] = float(v)
    return SLOTarget(**kw)


def targets_from_env(env: Optional[Mapping[str, str]] = None
                     ) -> List[SLOTarget]:
    """DYNAMO_TPU_SLO_TARGETS (JSON list of target specs) plus the scalar
    shorthand envs (one wildcard target). Malformed specs are logged and
    skipped — SLO config must never stop a worker from serving."""
    env = os.environ if env is None else env
    out: List[SLOTarget] = []
    raw = env.get(TARGETS_ENV)
    if raw:
        try:
            specs = json.loads(raw)
            if not isinstance(specs, list):
                raise ValueError("must be a JSON list")
            for spec in specs:
                out.append(target_from_dict(spec))
        except (ValueError, TypeError) as e:
            log.warning("ignoring malformed %s: %s", TARGETS_ENV, e)
    scalars: Dict[str, float] = {}
    # one read per literal name (not a SCALAR_ENVS loop) so the
    # env-registry lint can see each knob at its read site
    for field, v in (("ttft_ms", env.get("DYNAMO_TPU_SLO_TTFT_MS")),
                     ("itl_ms", env.get("DYNAMO_TPU_SLO_ITL_MS")),
                     ("error_rate", env.get("DYNAMO_TPU_SLO_ERROR_RATE")),
                     ("goal", env.get("DYNAMO_TPU_SLO_GOAL"))):
        if v:
            try:
                scalars[field] = float(v)
            except ValueError:
                log.warning("ignoring non-numeric SLO scalar %s=%r",
                            field, v)
    if set(scalars) - {"goal"}:
        out.append(SLOTarget(**scalars))
    return out


class SLOEngine:
    """Sliding-window SLO attainment/burn computed from histogram deltas.

    One instance per serving process (frontend or worker). All state is
    in-memory and bounded: ``max(window) / bucket_s`` time buckets plus
    the request-rate history ring."""

    def __init__(self, metrics: FrontendMetrics, role: str,
                 targets: Optional[Sequence[SLOTarget]] = None,
                 clock=time.time,
                 bucket_s: int = DEFAULT_BUCKET_S,
                 windows_s: Sequence[int] = DEFAULT_WINDOWS_S,
                 history_buckets: int = DEFAULT_HISTORY_BUCKETS):
        self.metrics = metrics
        self.role = role
        self.targets = list(targets if targets is not None
                            else targets_from_env())
        self.clock = clock
        self.bucket_s = max(1, int(bucket_s))
        self.windows_s = tuple(sorted(windows_s))
        depth = max(max(self.windows_s) // self.bucket_s, history_buckets)
        # each bucket: {"idx": int, "requests": int,
        #               "data": {(target_i, objective): [total, breaches]}}
        self._buckets: "collections.deque" = collections.deque(maxlen=depth)
        self.history_buckets = history_buckets
        self._cur: Optional[Dict[str, Any]] = None
        # cumulative snapshots keyed (target_i, objective, series labels)
        self._last: Dict[tuple, Tuple[float, float]] = {}
        # (target_i, objective) pairs that have ever matched an observed
        # series — selectors that never match real traffic emit no rows
        # (a typo'd model selector shows up as a MISSING series, not a
        # perpetually-green one)
        self._matched: set = set()
        self._last_requests = 0.0
        self._lock = threading.Lock()
        r = metrics.registry
        labelnames = ("slo", "objective", "window", "model", "role",
                      "tenant")
        self.attainment_gauge = Gauge(
            "dynamo_slo_attainment",
            "Fraction of requests meeting the SLO objective over the "
            "window (1.0 with no traffic)", r, labelnames=labelnames)
        self.burn_gauge = Gauge(
            "dynamo_slo_burn_rate",
            "SLO error-budget burn rate over the window (>1.0 = the "
            "objective's budget is burning down)", r, labelnames=labelnames)

    # ------------------------------------------------------------- ticking --
    def _advance(self, now: float) -> None:
        idx = int(now // self.bucket_s)
        if self._cur is None:
            self._cur = {"idx": idx, "requests": 0, "data": {}}
            return
        if idx < self._cur["idx"]:
            return  # clock went backwards: hold the current bucket
        jump = idx - self._cur["idx"]
        maxlen = self._buckets.maxlen or 1
        if jump > maxlen:
            # a huge gap (suspend, fake-clock leap): every old bucket is
            # out of any window — drop them instead of filling the gap
            self._buckets.clear()
            self._cur = {"idx": idx, "requests": 0, "data": {}}
            return
        while self._cur["idx"] < idx:
            self._buckets.append(self._cur)
            self._cur = {"idx": self._cur["idx"] + 1, "requests": 0,
                         "data": {}}

    def _bank(self, ti: int, objective: str, series_key: tuple,
              total: float, breaches: float) -> None:
        """Delta one series' cumulative (total, breaches) into the current
        bucket."""
        self._matched.add((ti, objective))
        key = (ti, objective, series_key)
        p_tot, p_breach = self._last.get(key, (0.0, 0.0))
        d_tot, d_breach = total - p_tot, breaches - p_breach
        self._last[key] = (total, breaches)
        if d_tot <= 0 and d_breach <= 0:
            return
        cell = self._cur["data"].setdefault((ti, objective), [0.0, 0.0])
        cell[0] += max(d_tot, 0.0)
        cell[1] += max(d_breach, 0.0)

    def _collect(self) -> None:
        m = self.metrics
        # request-rate history (planner v2 forecasting input)
        req_total = sum(m.requests_total.values().values())
        d_req = req_total - self._last_requests
        self._last_requests = req_total
        if d_req > 0:
            self._cur["requests"] += int(d_req)
        err_by_model: Dict[str, float] = {}
        for lbl, v in m.errors_total.values().items():
            model = dict(lbl).get("model", "")
            err_by_model[model] = err_by_model.get(model, 0.0) + v
        req_by_model: Dict[str, float] = {}
        for lbl, v in m.requests_total.values().items():
            model = dict(lbl).get("model", "")
            req_by_model[model] = req_by_model.get(model, 0.0) + v
        for ti, t in enumerate(self.targets):
            if not t.matches_role(self.role):
                continue
            tenant_scoped = t.tenant != "*"
            for objective, threshold_s, _budget in t.objectives():
                if objective == "error_rate":
                    if tenant_scoped:
                        continue  # no per-tenant error counter (docstring)
                    for model, reqs in req_by_model.items():
                        if not t.matches_model(model):
                            continue
                        self._bank(ti, objective, ("model", model),
                                   reqs, err_by_model.get(model, 0.0))
                    continue
                if tenant_scoped:
                    # per-tenant QoS selector: the tenant-labeled latency
                    # series (dynamo_tenant_*) are the source, so one
                    # tenant's tail can't hide in the model aggregate
                    hist = (m.tenant_ttft if objective == "ttft"
                            else m.tenant_itl)
                    for lbl, (good, total) in hist.good_total(
                            threshold_s).items():
                        if dict(lbl).get("tenant", "") != t.tenant:
                            continue
                        self._bank(ti, objective, lbl, total, total - good)
                    continue
                hist = m.ttft if objective == "ttft" else m.itl
                for lbl, (good, total) in hist.good_total(threshold_s).items():
                    model = dict(lbl).get("model", "")
                    if not t.matches_model(model):
                        continue
                    self._bank(ti, objective, lbl, total, total - good)

    def tick(self, now: Optional[float] = None) -> None:
        """Advance the bucket clock and bank histogram deltas. Called at
        scrape/debug time (and by tests under fake clocks) — between ticks
        the histograms accumulate on their own."""
        with self._lock:
            self._advance(self.clock() if now is None else now)
            self._collect()

    # ---------------------------------------------------------- evaluation --
    def _window_sum(self, window_s: int, ti: int, objective: str
                    ) -> Tuple[float, float]:
        n = max(1, window_s // self.bucket_s)
        lo = self._cur["idx"] - n  # buckets with idx > lo are in-window
        tot = br = 0.0
        cell = self._cur["data"].get((ti, objective))
        if cell:
            tot, br = cell[0], cell[1]
        for b in self._buckets:
            if b["idx"] > lo:
                cell = b["data"].get((ti, objective))
                if cell:
                    tot += cell[0]
                    br += cell[1]
        return tot, br

    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Attainment + burn rate per (target, objective, window)."""
        self.tick(now)
        out: List[Dict[str, Any]] = []
        with self._lock:
            for ti, t in enumerate(self.targets):
                if not t.matches_role(self.role):
                    continue
                for objective, threshold_s, budget in t.objectives():
                    if (ti, objective) not in self._matched:
                        continue
                    for w in self.windows_s:
                        tot, br = self._window_sum(w, ti, objective)
                        frac = (br / tot) if tot > 0 else 0.0
                        out.append({
                            "slo": t.label,
                            "objective": objective,
                            "window": WINDOW_LABELS.get(w, f"{w}s"),
                            "window_s": w,
                            "model": t.model,
                            "tenant": t.tenant,
                            "role": self.role,
                            "threshold_s": threshold_s,
                            "requests": int(tot),
                            "breaches": int(br),
                            "attainment": round(1.0 - frac, 6),
                            "burn_rate": round(frac / budget, 4)
                            if budget > 0 else 0.0,
                        })
        return out

    def refresh_gauges(self, now: Optional[float] = None) -> None:
        """Scrape-time gauge refresh (the /metrics handlers call this)."""
        for row in self.evaluate(now):
            labels = dict(slo=row["slo"], objective=row["objective"],
                          window=row["window"], model=row["model"],
                          role=row["role"], tenant=row["tenant"])
            self.attainment_gauge.set(row["attainment"], **labels)
            self.burn_gauge.set(row["burn_rate"], **labels)

    # ------------------------------------------------------------- history --
    def history(self) -> List[Dict[str, Any]]:
        """Per-bucket request counts, oldest first, current partial bucket
        last — exact counts, not rates (the forecaster derives rates)."""
        with self._lock:
            rows = [{"t": b["idx"] * self.bucket_s, "requests": b["requests"]}
                    for b in self._buckets]
            if self._cur is not None:
                rows.append({"t": self._cur["idx"] * self.bucket_s,
                             "requests": self._cur["requests"],
                             "partial": True})
        return rows[-self.history_buckets:]

    def debug_payload(self, include_history: bool = False) -> Dict[str, Any]:
        """The GET /debug/slo body (frontend + worker servers)."""
        payload: Dict[str, Any] = {
            "role": self.role,
            "bucket_s": self.bucket_s,
            "windows_s": list(self.windows_s),
            "targets": [t.to_dict() for t in self.targets],
            "evaluations": self.evaluate(),
        }
        if include_history:
            payload["history"] = self.history()
        return payload


def debug_slo_payload(engine: Optional[SLOEngine],
                      qs: Mapping[str, List[str]]) -> Dict[str, Any]:
    """Shared /debug/slo handler body: honors ?history=1."""
    if engine is None:
        return {"targets": [], "evaluations": [],
                "note": "no SLO engine attached"}
    want_history = (qs.get("history") or ["0"])[0] not in ("0", "", "false")
    return engine.debug_payload(include_history=want_history)
