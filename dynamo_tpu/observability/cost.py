"""Per-tenant cost attribution: chip-seconds and HBM-byte-seconds.

Accumulated from the same per-step evidence the flight recorder sees:
every executed engine segment (decode window, mixed step, prefill, chunk)
calls `account(dur_s, shares, holdings)` with

- ``shares``   — tenant → work units this segment.  Decode slots are one
  unit each; prefill/chunk work is units = tokens, so a mixed step splits
  its wall time between the chunk's tenant (by token share) and the
  decode slots exactly as the ISSUE's attribution rule prescribes.
- ``holdings`` — tenant → KV bytes held on-device during the segment
  (sequence pages + inflight-prefill pages + parked disagg pages).

Chip-seconds for a tenant = dur_s × its unit share; byte-seconds accrue
bytes × dur_s.  Both are accumulated next to engine-level totals in the
SAME call, so the conservation invariant — per-tenant shares sum to the
engine totals — holds by construction and is assertable at any instant
(tests/test_cost_accounting.py; `/debug/costs` exposes both sides).

The frontend aggregates worker rollups fleet-wide: the worker heartbeat
carries `rollup()` in its stats payload, the existing gossip plane relays
registrations between frontend replicas, and `merge_rollups` sums them.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Iterable, Mapping


class CostLedger:
    """Monotonic per-tenant cost counters with engine-total conservation."""

    def __init__(self):
        self._lock = threading.Lock()
        self.chip_seconds: Dict[str, float] = {}  # guarded_by: _lock
        self.hbm_byte_seconds: Dict[str, float] = {}  # guarded_by: _lock
        self.chip_seconds_total = 0.0  # guarded_by: _lock
        self.hbm_byte_seconds_total = 0.0  # guarded_by: _lock
        self.segments_total = 0  # guarded_by: _lock
        # optional tenant -> tier classifier ("batch" | "interactive"),
        # wired once at engine construction from the QoS registry so the
        # preemptible batch tier prices as its own rollup row; read-only
        # after wiring (no lock needed)
        self.tier_of = None

    def account(self, dur_s: float, shares: Mapping[str, float],
                holdings: Mapping[str, float]) -> None:
        """Attribute one executed segment.  Totals only advance by exactly
        what gets distributed, so sum(per-tenant) == total always."""
        if dur_s <= 0.0:
            return
        unit_total = float(sum(shares.values()))
        byte_total = float(sum(holdings.values()))
        with self._lock:
            self.segments_total += 1
            if unit_total > 0.0:
                self.chip_seconds_total += dur_s
                for tenant, units in shares.items():
                    if units <= 0.0:
                        continue
                    self.chip_seconds[tenant] = (
                        self.chip_seconds.get(tenant, 0.0)
                        + dur_s * (units / unit_total))
            if byte_total > 0.0:
                self.hbm_byte_seconds_total += byte_total * dur_s
                for tenant, nbytes in holdings.items():
                    if nbytes <= 0.0:
                        continue
                    self.hbm_byte_seconds[tenant] = (
                        self.hbm_byte_seconds.get(tenant, 0.0)
                        + nbytes * dur_s)

    # ------------------------------------------------------------ export ---
    def chip_seconds_snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self.chip_seconds)

    def hbm_byte_seconds_snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self.hbm_byte_seconds)

    def per_tenant(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            tenants = set(self.chip_seconds) | set(self.hbm_byte_seconds)
            return {t: {"chip_seconds": self.chip_seconds.get(t, 0.0),
                        "hbm_byte_seconds": self.hbm_byte_seconds.get(t, 0.0)}
                    for t in sorted(tenants)}

    def rollup(self) -> Dict[str, Any]:
        """`GET /debug/costs` body / heartbeat `stats["costs"]` payload."""
        tier_of = self.tier_of
        with self._lock:
            tenants = set(self.chip_seconds) | set(self.hbm_byte_seconds)
            out = {
                "tenants": {
                    t: {"chip_seconds":
                        round(self.chip_seconds.get(t, 0.0), 6),
                        "hbm_byte_seconds":
                        round(self.hbm_byte_seconds.get(t, 0.0), 3)}
                    for t in sorted(tenants)},
                "totals": {
                    "chip_seconds": round(self.chip_seconds_total, 6),
                    "hbm_byte_seconds":
                    round(self.hbm_byte_seconds_total, 3)},
                "segments_total": self.segments_total,
            }
            if tier_of is not None:
                tiers: Dict[str, Dict[str, float]] = {}
                for t in tenants:
                    row = tiers.setdefault(
                        tier_of(t),
                        {"chip_seconds": 0.0, "hbm_byte_seconds": 0.0})
                    row["chip_seconds"] += self.chip_seconds.get(t, 0.0)
                    row["hbm_byte_seconds"] += \
                        self.hbm_byte_seconds.get(t, 0.0)
                out["tiers"] = {
                    tier: {k: round(v, 6) for k, v in row.items()}
                    for tier, row in sorted(tiers.items())}
        return out


def merge_rollups(rollups: Iterable[Mapping[str, Any]]) -> Dict[str, Any]:
    """Fleet-wide sum of per-worker `rollup()` payloads (frontend
    `/debug/costs`).  Tolerates malformed/missing entries — a worker on an
    older build just contributes nothing."""
    tenants: Dict[str, Dict[str, float]] = {}
    tiers: Dict[str, Dict[str, float]] = {}
    totals = {"chip_seconds": 0.0, "hbm_byte_seconds": 0.0}
    workers = 0
    for r in rollups:
        if not isinstance(r, Mapping):
            continue
        workers += 1
        for t, c in (r.get("tenants") or {}).items():
            if not isinstance(c, Mapping):
                continue
            agg = tenants.setdefault(
                t, {"chip_seconds": 0.0, "hbm_byte_seconds": 0.0})
            agg["chip_seconds"] += float(c.get("chip_seconds", 0.0))
            agg["hbm_byte_seconds"] += float(c.get("hbm_byte_seconds", 0.0))
        for tier, c in (r.get("tiers") or {}).items():
            if not isinstance(c, Mapping):
                continue
            agg = tiers.setdefault(
                tier, {"chip_seconds": 0.0, "hbm_byte_seconds": 0.0})
            agg["chip_seconds"] += float(c.get("chip_seconds", 0.0))
            agg["hbm_byte_seconds"] += float(c.get("hbm_byte_seconds", 0.0))
        tot = r.get("totals") or {}
        totals["chip_seconds"] += float(tot.get("chip_seconds", 0.0))
        totals["hbm_byte_seconds"] += float(tot.get("hbm_byte_seconds", 0.0))
    out = {"tenants": {t: {k: round(v, 6) for k, v in c.items()}
                       for t, c in sorted(tenants.items())},
           "totals": {k: round(v, 6) for k, v in totals.items()},
           "workers": workers}
    if tiers:
        out["tiers"] = {tier: {k: round(v, 6) for k, v in c.items()}
                        for tier, c in sorted(tiers.items())}
    return out
