"""Distributed request tracing, stdlib-only.

One request crosses four processes in the disaggregated topology —
frontend -> router decision -> decode worker -> prefill worker — and the
latency pathologies live in the hops, not the processes. This package
carries a W3C `traceparent` context across both transports (HTTP headers
and NATS message headers), records spans into a bounded in-process ring
buffer, and exports them OTLP-JSON-shaped at `GET /debug/spans` so an
external collector (or a test) can reassemble the trace.

- `context`  — traceparent parse/format + trace/span ID generation.
- `tracing`  — Tracer/Span + the ring-buffer SpanCollector and OTLP-dict
               export (no OTLP dependency; the shapes match
               `ExportTraceServiceRequest` so a collector can ingest them).

Kill switch: `DYNAMO_TPU_TRACE=0` short-circuits span creation to a no-op
singleton (context propagation still works, so downstream services keep
their correlation ids).
"""

from dynamo_tpu.observability.context import (  # noqa: F401
    TraceContext,
    extract_context,
    format_traceparent,
    inject_context,
    new_span_id,
    new_trace_id,
    parse_traceparent,
)
from dynamo_tpu.observability.tracing import (  # noqa: F401
    NOOP_SPAN,
    Span,
    SpanCollector,
    Tracer,
    get_collector,
    tracing_enabled,
)
