"""Endpoint load generator — the aiperf analogue.

Drives an OpenAI-compatible /v1/chat/completions endpoint with streaming
requests from a thread pool, recording per-request TTFT, ITL, end-to-end
latency, and token counts. Stdlib-only (urllib + threads) so it runs in any
cluster image. Consumed by `benchmarks.utils.benchmark`
(/root/reference/run-benchmarks.sh:56-68 invokes the reference's equivalent).
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import random
import socket
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class RequestResult:
    ok: bool
    ttft_s: float = 0.0          # time to first streamed token
    latency_s: float = 0.0       # end-to-end
    itl_s: List[float] = dataclasses.field(default_factory=list)
    input_tokens: int = 0
    output_tokens: int = 0
    error: str = ""
    status: int = 0              # HTTP status of the LAST attempt
    shed: bool = False           # last attempt was a 429/503 admission shed
    retry_after_s: float = 0.0   # server's Retry-After on that shed
    retries: int = 0             # re-queues before this result
    target: str = ""             # frontend URL that served the LAST attempt
    resumes: int = 0             # mid-stream reconnects (dynamo_resume)


@dataclasses.dataclass
class LoadConfig:
    endpoint_url: str
    model: str
    num_requests: int = 128
    concurrency: int = 4
    input_len: int = 128          # synthetic prompt length (words)
    max_tokens: int = 64
    timeout_s: float = 300.0
    prompt: Optional[str] = None  # overrides the synthetic prompt
    # statistics hygiene: warmup requests run first (compile/caches/batch
    # ramp) and are EXCLUDED from results; duration_s switches the timed
    # phase from a fixed count to a fixed wall-clock window, so percentile
    # sample size scales with throughput instead of being fixed at
    # num_requests (p99 over 32 samples is noise)
    warmup_requests: int = 0
    duration_s: Optional[float] = None
    # admission-shed etiquette: a 429/503 with Retry-After is the server
    # MANAGING load, not failing — honor it with a jittered re-queue
    # (±20%, mirroring the server's own retry_after_value jitter) instead
    # of counting a hard failure; max_retries bounds the patience
    honor_retry_after: bool = True
    max_retries: int = 3
    # open-loop arrival schedule (run_open_loop): arrivals follow the
    # planner scenario schedules (dynamo_tpu.planner.scenarios — the SAME
    # math the autoscaling simulator replays) instead of closing the loop
    # on completions. kinds: steady | ramp | spike | diurnal
    schedule: Optional[str] = None
    base_rps: float = 1.0
    peak_rps: float = 10.0
    schedule_params: Dict[str, Any] = dataclasses.field(
        default_factory=dict)
    max_outstanding: int = 1024   # open-loop thread-safety valve
    # HA frontend plane: N frontend replicas behind one logical service.
    # endpoint_urls (when non-empty) overrides endpoint_url; requests
    # round-robin across them, results carry the serving target, and a
    # mid-stream connection reset reconnects to the NEXT replica with a
    # dynamo_resume cursor (docs/robustness.md "HA frontend plane")
    endpoint_urls: List[str] = dataclasses.field(default_factory=list)
    resume_on_reset: bool = True
    _rr: List[int] = dataclasses.field(
        default_factory=lambda: [0], repr=False)
    _rr_lock: threading.Lock = dataclasses.field(
        default_factory=threading.Lock, repr=False)

    def targets(self) -> List[str]:
        return [u for u in self.endpoint_urls if u] or [self.endpoint_url]

    def next_target(self) -> str:
        urls = self.targets()
        with self._rr_lock:
            i = self._rr[0]
            self._rr[0] = (i + 1) % len(urls)
        return urls[i % len(urls)]


def _synthetic_prompt(n_words: int, seed: int) -> str:
    """Deterministic filler prompt ~n_words long; varies per request so
    prefix-cache routing doesn't collapse every request onto one worker."""
    words = ["alpha", "ocean", "matrix", "signal", "vector", "photon",
             "kernel", "lattice", "tensor", "stream"]
    body = " ".join(words[(seed + i) % len(words)] for i in range(n_words))
    return f"[req {seed}] Repeat and continue this text: {body}"


def run_one(cfg: LoadConfig, seed: int) -> RequestResult:
    prompt = cfg.prompt or _synthetic_prompt(cfg.input_len, seed)
    base_body: Dict[str, Any] = {
        "model": cfg.model,
        "messages": [{"role": "user", "content": prompt}],
        "max_tokens": cfg.max_tokens,
        "temperature": 0,
        "stream": True,
        "stream_options": {"include_usage": True},
    }
    target = cfg.next_target()
    res = RequestResult(ok=False, target=target)
    start = time.perf_counter()
    last_tok: Optional[float] = None
    n_deltas = 0
    usage_tokens: Optional[int] = None
    # HA resume state: the client counts its OWN delivered content chars
    # and remembers the stream's response id; on a mid-stream connection
    # reset it re-POSTs the original body + a dynamo_resume cursor to the
    # NEXT frontend replica, which re-emits exactly the chars past the
    # cursor from the replicated journal (serving/ha.py)
    response_id: Optional[str] = None
    delivered_chars = 0
    while True:
        body_obj = dict(base_body)
        if res.resumes:
            body_obj["dynamo_resume"] = {
                "response_id": response_id,
                "delivered_chars": delivered_chars,
            }
        req = urllib.request.Request(
            target.rstrip("/") + "/v1/chat/completions",
            data=json.dumps(body_obj).encode(),
            headers={"Content-Type": "application/json"}, method="POST",
        )
        saw_done = False
        mid_stream_err: Optional[str] = None
        try:
            with urllib.request.urlopen(req, timeout=cfg.timeout_s) as resp:
                for raw in resp:
                    line = raw.decode("utf-8", "replace").strip()
                    if not line.startswith("data:"):
                        continue
                    payload = line[len("data:"):].strip()
                    if payload == "[DONE]":
                        saw_done = True
                        break
                    try:
                        chunk = json.loads(payload)
                    except json.JSONDecodeError:
                        continue
                    if response_id is None and chunk.get("id"):
                        response_id = str(chunk["id"])
                    usage = chunk.get("usage")
                    if usage:
                        res.input_tokens = usage.get("prompt_tokens", 0)
                        usage_tokens = usage.get("completion_tokens")
                    choices = chunk.get("choices") or []
                    if not choices:
                        continue
                    delta = (choices[0].get("delta") or {}).get("content")
                    if delta:
                        now = time.perf_counter()
                        if last_tok is None:
                            res.ttft_s = now - start
                        else:
                            res.itl_s.append(now - last_tok)
                        last_tok = now
                        n_deltas += 1
                        delivered_chars += len(delta)
                    elif (choices[0].get("finish_reason") is not None
                            and last_tok is None):
                        # a stream can legally finish with NO visible text
                        # (the detokenizer holds back bytes that never
                        # complete a codepoint); the finish chunk is then
                        # the first — and only — token-arrival signal, so
                        # TTFT lands there instead of reading 0
                        res.ttft_s = time.perf_counter() - start
        except urllib.error.HTTPError as e:
            res.latency_s = time.perf_counter() - start
            res.status = e.code
            res.error = f"HTTP {e.code}"
            if e.code in (429, 503):
                # admission shed: the server is load-managing, not broken
                # — record its Retry-After so the caller can re-queue
                res.shed = True
                try:
                    res.retry_after_s = float(e.headers.get("Retry-After")
                                              or 1.0)
                except (TypeError, ValueError):
                    res.retry_after_s = 1.0
            try:
                e.close()
            except Exception:  # noqa: BLE001
                pass
            return res
        except (ConnectionResetError, BrokenPipeError, ConnectionError,
                http.client.HTTPException, socket.error) as e:
            mid_stream_err = f"{type(e).__name__}: {e}"
        except Exception as e:  # noqa: BLE001 — load gen records, never raises
            res.latency_s = time.perf_counter() - start
            res.error = f"{type(e).__name__}: {e}"
            return res
        if saw_done:
            res.latency_s = time.perf_counter() - start
            # exact server-side count when stream usage is on; delta count
            # otherwise (deltas may under-count: servers can batch tokens
            # per SSE event, and some token ids decode to empty text)
            res.output_tokens = (usage_tokens if usage_tokens is not None
                                 else n_deltas)
            res.ok = res.output_tokens > 0
            if not res.ok:
                res.error = "no tokens streamed"
            return res
        # connection dropped (reset, or EOF without [DONE]): the frontend
        # replica died mid-stream. Resume through the next replica if the
        # stream is identifiable; otherwise record the failure
        if (cfg.resume_on_reset and response_id is not None
                and res.resumes < cfg.max_retries
                and len(cfg.targets()) > 0):
            res.resumes += 1
            target = cfg.next_target()
            res.target = target
            continue
        res.latency_s = time.perf_counter() - start
        res.error = mid_stream_err or "stream ended without [DONE]"
        return res


def run_one_with_retries(cfg: LoadConfig, seed: int,
                         deadline: Optional[float] = None) -> RequestResult:
    """run_one plus Retry-After etiquette: a 429/503 shed re-queues after
    the server's own Retry-After (jittered ±20% so a synchronized shed
    doesn't return as a synchronized retry stampede), up to
    cfg.max_retries times or until `deadline`."""
    attempts = 0
    while True:
        res = run_one(cfg, seed)
        res.retries = attempts
        if (not res.shed or not cfg.honor_retry_after
                or attempts >= cfg.max_retries):
            return res
        wait = max(0.05, res.retry_after_s) * random.uniform(0.8, 1.2)
        if deadline is not None \
                and time.perf_counter() + wait >= deadline:
            return res  # no budget left to honor the hint
        time.sleep(wait)
        attempts += 1


def _run_phase(cfg: LoadConfig, n_requests: Optional[int],
               deadline: Optional[float], seed_base: int
               ) -> List[RequestResult]:
    """Closed-loop phase: `concurrency` workers pull request ids until the
    count is exhausted (count mode) or the deadline passes (duration mode —
    requests already in flight at the deadline run to completion, so the
    tail isn't censored toward fast requests)."""
    results: List[RequestResult] = []
    next_id = [0]
    lock = threading.Lock()

    def worker():
        while True:
            with lock:
                if n_requests is not None and next_id[0] >= n_requests:
                    return
                if deadline is not None and time.perf_counter() >= deadline:
                    return
                rid = next_id[0]
                next_id[0] += 1
            r = run_one_with_retries(cfg, seed_base + rid,
                                     deadline=deadline)
            with lock:
                results.append(r)

    threads = [
        threading.Thread(target=worker, daemon=True, name=f"loadgen-{i}")
        for i in range(max(1, cfg.concurrency))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def run_load_timed(cfg: LoadConfig) -> tuple:
    """Warmup (excluded) then the timed phase (count- or duration-based).
    Returns (results, timed_wall_s) — the wall clock covers ONLY the timed
    phase, so throughput is never diluted by warmup compiles."""
    if cfg.warmup_requests > 0:
        _run_phase(cfg, cfg.warmup_requests, None, seed_base=1_000_000)
    t0 = time.perf_counter()
    if cfg.duration_s is not None:
        results = _run_phase(cfg, None, t0 + cfg.duration_s, seed_base=0)
    else:
        results = _run_phase(cfg, cfg.num_requests, None, seed_base=0)
    return results, time.perf_counter() - t0


def run_load(cfg: LoadConfig) -> List[RequestResult]:
    return run_load_timed(cfg)[0]


# ------------------------------------------------------------- open loop --
def run_open_loop(cfg: LoadConfig) -> tuple:
    """Open-loop phase: arrivals follow cfg.schedule (steady / ramp /
    spike / diurnal — dynamo_tpu.planner.scenarios, the SAME schedule
    math the autoscaling simulator replays in CI) regardless of how fast
    the server answers, which is what actually exercises an autoscaler:
    a closed loop self-throttles exactly when the system is saturated.

    Every arrival gets its own thread (bounded by cfg.max_outstanding —
    past the bound arrivals are recorded as local sheds rather than
    silently dropped). Returns (results, wall_s). Requires cfg.duration_s
    and cfg.schedule."""
    from dynamo_tpu.planner.scenarios import schedule_rate

    if not cfg.schedule or not cfg.duration_s:
        raise ValueError("run_open_loop needs cfg.schedule and "
                         "cfg.duration_s")
    if cfg.warmup_requests > 0:
        _run_phase(cfg, cfg.warmup_requests, None, seed_base=1_000_000)
    results: List[RequestResult] = []
    lock = threading.Lock()
    outstanding = [0]
    threads: List[threading.Thread] = []

    def fire(rid: int, deadline: float):
        r = run_one_with_retries(cfg, rid, deadline=deadline)
        with lock:
            results.append(r)
            outstanding[0] -= 1

    t0 = time.perf_counter()
    deadline = t0 + cfg.duration_s
    acc = 0.0
    rid = 0
    tick_s = 0.05
    while True:
        now = time.perf_counter()
        if now >= deadline:
            break
        rate = schedule_rate(cfg.schedule, now - t0, cfg.duration_s,
                             cfg.base_rps, cfg.peak_rps,
                             **cfg.schedule_params)
        acc += rate * tick_s
        n = int(acc)
        acc -= n
        for _ in range(n):
            with lock:
                if outstanding[0] >= cfg.max_outstanding:
                    shed = RequestResult(
                        ok=False, shed=True,
                        error="loadgen max_outstanding reached")
                    results.append(shed)
                    continue
                outstanding[0] += 1
            t = threading.Thread(target=fire, args=(rid, deadline),
                                 daemon=True,
                                 name=f"loadgen-open-{rid}")
            rid += 1
            t.start()
            threads.append(t)
        time.sleep(tick_s)
    for t in threads:  # in-flight arrivals run to completion (no censor)
        t.join(timeout=cfg.timeout_s)
    return results, time.perf_counter() - t0
