"""Endpoint benchmark CLI — `python3 -m benchmarks.utils.benchmark`.

CLI contract mirrors the module the reference's run-benchmarks.sh invokes
(`python3 -m benchmarks.utils.benchmark --benchmark-name … --endpoint-url …
--model … --output-dir …`, /root/reference/run-benchmarks.sh:56-68), so the
wrapper script runs unchanged. Sweeps concurrency levels against the
OpenAI-compatible endpoint and writes per-level JSON + a summary with tok/s,
tok/s/chip, and TTFT/ITL/latency percentiles.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import statistics
from typing import Dict, List

from benchmarks.utils.loadgen import (
    LoadConfig, RequestResult, run_load_timed,
)


def _pctl(values: List[float], q: float) -> float:
    if not values:
        return 0.0
    values = sorted(values)
    idx = min(len(values) - 1, max(0, int(round(q / 100.0 * (len(values) - 1)))))
    return values[idx]


def _hist_quantile(buckets: List[tuple], q: float) -> float:
    """Quantile estimate from cumulative (le, count) pairs (upper-edge
    bound, the Prometheus convention)."""
    if not buckets:
        return 0.0
    total = buckets[-1][1]
    if total <= 0:
        return 0.0
    target = q * total
    prev_edge = 0.0
    for le, c in buckets:
        if c >= target:
            return le if le != float("inf") else prev_edge
        prev_edge = le
    return prev_edge


def server_histogram_pctls(endpoint_url: str) -> Dict[str, Dict[str, float]]:
    """Scrape the endpoint's own /metrics and derive TTFT/ITL percentiles
    from the serving histograms — reported ALONGSIDE the loadgen's
    client-side measurements so the two latency sources cross-check each
    other (server histograms can't see client/network time; the client
    can't see per-model breakdowns). Empty dict when the endpoint exposes
    no scrape."""
    import urllib.request

    try:
        with urllib.request.urlopen(
                endpoint_url.rstrip("/") + "/metrics", timeout=5) as r:
            text = r.read().decode("utf-8", "replace")
    except Exception:
        return {}
    series = {
        "ttft_ms": "dynamo_frontend_time_to_first_token_seconds_bucket",
        "itl_ms": "dynamo_frontend_inter_token_latency_seconds_bucket",
    }
    out: Dict[str, Dict[str, float]] = {}
    for key, name in series.items():
        acc: Dict[float, float] = {}
        for ln in text.splitlines():
            if not ln.startswith(name + "{"):
                continue
            try:
                labels, value = ln.rsplit(" ", 1)
                le_part = labels.split('le="', 1)[1].split('"', 1)[0]
                le = float("inf") if le_part == "+Inf" else float(le_part)
                acc[le] = acc.get(le, 0.0) + float(value)
            except (IndexError, ValueError):
                continue
        buckets = sorted(acc.items())
        if buckets and buckets[-1][1] > 0:
            out[key] = {
                "p50": round(_hist_quantile(buckets, 0.50) * 1e3, 2),
                "p90": round(_hist_quantile(buckets, 0.90) * 1e3, 2),
                "p99": round(_hist_quantile(buckets, 0.99) * 1e3, 2),
            }
    return out


def summarize(results: List[RequestResult], wall_s: float, num_chips: int) -> Dict:
    ok = [r for r in results if r.ok]
    out_toks = sum(r.output_tokens for r in ok)
    in_toks = sum(r.input_tokens for r in ok)
    # only requests that actually streamed text contribute latency samples
    ttfts = [r.ttft_s for r in ok if r.ttft_s > 0]
    lats = [r.latency_s for r in ok]
    itls = [itl for r in ok for itl in r.itl_s]
    return {
        "requests": len(results),
        "successful": len(ok),
        "failed": len(results) - len(ok),
        "wall_s": round(wall_s, 3),
        "input_tokens": in_toks,
        "output_tokens": out_toks,
        "output_tok_per_s": round(out_toks / wall_s, 2) if wall_s else 0.0,
        "output_tok_per_s_per_chip": (
            round(out_toks / wall_s / num_chips, 2) if wall_s else 0.0
        ),
        "request_per_s": round(len(ok) / wall_s, 3) if wall_s else 0.0,
        "ttft_ms": {
            "p50": round(_pctl(ttfts, 50) * 1e3, 1),
            "p90": round(_pctl(ttfts, 90) * 1e3, 1),
            "p99": round(_pctl(ttfts, 99) * 1e3, 1),
            "mean": round(statistics.fmean(ttfts) * 1e3, 1) if ttfts else 0.0,
        },
        "itl_ms": {
            "p50": round(_pctl(itls, 50) * 1e3, 2),
            "p90": round(_pctl(itls, 90) * 1e3, 2),
            "p99": round(_pctl(itls, 99) * 1e3, 2),
            "mean": round(statistics.fmean(itls) * 1e3, 2) if itls else 0.0,
        },
        "latency_ms": {
            "p50": round(_pctl(lats, 50) * 1e3, 1),
            "p90": round(_pctl(lats, 90) * 1e3, 1),
            "p99": round(_pctl(lats, 99) * 1e3, 1),
        },
        "errors": sorted({r.error for r in results if r.error})[:5],
    }


def _run_open_loop_scenario(args) -> int:
    """One open-loop schedule run (--schedule): arrivals are paced by the
    scenario curve, sheds are re-queued per Retry-After, and the report
    carries shed/retry accounting next to the latency summary."""
    from benchmarks.utils.loadgen import run_open_loop

    if not args.duration_s:
        print("[benchmark] --schedule requires --duration-s")
        return 2
    cfg = LoadConfig(
        endpoint_url=args.endpoint_url, model=args.model,
        endpoint_urls=args.endpoint_urls,
        input_len=args.isl, max_tokens=args.osl, timeout_s=args.timeout,
        warmup_requests=(args.warmup_requests
                         if args.warmup_requests is not None else 8),
        duration_s=args.duration_s, schedule=args.schedule,
        base_rps=args.base_rps, peak_rps=args.peak_rps,
    )
    print(f"[benchmark] {args.benchmark_name}: open-loop "
          f"schedule={args.schedule} {args.base_rps}->{args.peak_rps} rps "
          f"over {args.duration_s}s")
    results, wall = run_open_loop(cfg)
    summary = summarize(results, wall, args.num_chips)
    summary["schedule"] = {
        "kind": args.schedule, "base_rps": args.base_rps,
        "peak_rps": args.peak_rps, "duration_s": args.duration_s,
        "arrivals": len(results),
        "shed_final": sum(1 for r in results if r.shed),
        "retries_total": sum(r.retries for r in results),
    }
    summary["server_histogram"] = (
        server_histogram_pctls(args.endpoint_url) or None)
    out_path = os.path.join(
        args.output_dir, f"{args.benchmark_name}_{args.schedule}.json")
    with open(out_path, "w") as f:
        json.dump({"summary": summary,
                   "results": [dataclasses.asdict(r) for r in results]},
                  f, indent=2)
    print(f"[benchmark] wrote {out_path} "
          f"({summary['schedule']['arrivals']} arrivals, "
          f"{summary['schedule']['shed_final']} shed, "
          f"{summary['schedule']['retries_total']} retries)")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="benchmarks.utils.benchmark")
    p.add_argument("--benchmark-name", required=True)
    p.add_argument("--endpoint-url", required=True,
                   help="endpoint base URL; a comma-separated list "
                        "round-robins across frontend replicas (HA plane: "
                        "results carry the serving target and mid-stream "
                        "resets resume on the next replica)")
    p.add_argument("--model", required=True)
    p.add_argument("--output-dir", required=True)
    p.add_argument("--concurrency", default="1,2,4,8",
                   help="comma-separated concurrency sweep")
    p.add_argument("--requests-per-level", type=int, default=128)
    p.add_argument("--duration-s", type=float, default=None,
                   help="per-level wall-clock window; overrides "
                        "--requests-per-level so percentile sample size "
                        "scales with throughput")
    p.add_argument("--warmup-requests", type=int, default=None,
                   help="excluded warmup requests per level "
                        "(default: 2 x concurrency, min 8)")
    p.add_argument("--isl", type=int, default=128,
                   help="synthetic input length (words)")
    p.add_argument("--osl", type=int, default=64, help="max output tokens")
    p.add_argument("--num-chips", type=int,
                   default=int(os.environ.get("NUM_CHIPS", "1")),
                   help="chips behind the endpoint, for tok/s/chip")
    p.add_argument("--timeout", type=float, default=300.0)
    # open-loop scenario mode (docs/autoscaling.md): arrivals follow a
    # planner scenario schedule instead of closing the loop on
    # completions — the knob that actually exercises an autoscaler, and
    # the SAME schedule math the CI simulator replays
    p.add_argument("--schedule", default=None,
                   choices=["steady", "ramp", "spike", "diurnal"],
                   help="open-loop arrival schedule (requires "
                        "--duration-s; replaces the concurrency sweep)")
    p.add_argument("--base-rps", type=float, default=1.0)
    p.add_argument("--peak-rps", type=float, default=10.0)
    args = p.parse_args(argv)
    # comma-separated --endpoint-url fans out across HA frontend replicas;
    # the first target keeps serving the single-URL paths (server histogram
    # scrape, report header)
    args.endpoint_urls = [u.strip() for u in args.endpoint_url.split(",")
                          if u.strip()]
    args.endpoint_url = args.endpoint_urls[0]

    os.makedirs(args.output_dir, exist_ok=True)
    if args.schedule:
        return _run_open_loop_scenario(args)
    levels = [int(c) for c in args.concurrency.split(",") if c.strip()]
    sweep = []
    # a falsy --duration-s (0) means count mode everywhere, so the log line,
    # LoadConfig, and loadgen's `is not None` check can never disagree
    duration_s = args.duration_s or None
    for conc in levels:
        warmup = (args.warmup_requests if args.warmup_requests is not None
                  else max(8, 2 * conc))
        cfg = LoadConfig(
            endpoint_url=args.endpoint_url,
            endpoint_urls=args.endpoint_urls,
            model=args.model,
            num_requests=args.requests_per_level,
            concurrency=conc,
            input_len=args.isl,
            max_tokens=args.osl,
            timeout_s=args.timeout,
            warmup_requests=warmup,
            duration_s=duration_s,
        )
        load_desc = (f"duration={duration_s}s" if duration_s
                     else f"requests={cfg.num_requests}")
        print(f"[benchmark] {args.benchmark_name}: concurrency={conc} "
              f"{load_desc} warmup={warmup} isl~{args.isl}w osl={args.osl}")
        results, wall = run_load_timed(cfg)
        summary = summarize(results, wall, args.num_chips)
        summary["concurrency"] = conc
        summary["warmup_excluded"] = warmup
        # both latency sources side by side: client-measured (above) and
        # the server's own histogram-derived percentiles — upper-edge
        # bounds over the whole scrape lifetime, so expect them coarser
        summary["server_histogram"] = (
            server_histogram_pctls(args.endpoint_url) or None)
        sweep.append(summary)
        print(f"[benchmark]   -> {summary['output_tok_per_s']} tok/s, "
              f"TTFT p50 {summary['ttft_ms']['p50']}ms, "
              f"ITL p50 {summary['itl_ms']['p50']}ms, "
              f"{summary['failed']} failed")
        level_path = os.path.join(
            args.output_dir, f"{args.benchmark_name}_c{conc}.json"
        )
        with open(level_path, "w") as f:
            json.dump(
                {
                    "summary": summary,
                    "results": [dataclasses.asdict(r) for r in results],
                },
                f, indent=2,
            )

    best = max(sweep, key=lambda s: s["output_tok_per_s"]) if sweep else {}
    report = {
        "benchmark_name": args.benchmark_name,
        "endpoint_url": args.endpoint_url,
        "endpoint_urls": args.endpoint_urls,
        "model": args.model,
        "num_chips": args.num_chips,
        "isl_words": args.isl,
        "osl_tokens": args.osl,
        "sweep": sweep,
        "best": best,
    }
    out_path = os.path.join(args.output_dir, f"{args.benchmark_name}_summary.json")
    with open(out_path, "w") as f:
        json.dump(report, f, indent=2)
    print(f"[benchmark] wrote {out_path}")
    if best:
        print(json.dumps({
            "metric": "output_tok_per_s_per_chip",
            "value": best["output_tok_per_s_per_chip"],
            "unit": "tok/s/chip",
            "ttft_p50_ms": best["ttft_ms"]["p50"],
            "itl_p50_ms": best["itl_ms"]["p50"],
        }))
    any_ok = any(s["successful"] for s in sweep)
    return 0 if any_ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
