"""Plot benchmark sweeps — `python3 -m benchmarks.utils.plot --data-dir D`.

Mirror of the reference's optional plotting step
(/root/reference/run-benchmarks.sh:70-72). Reads the *_summary.json files
written by benchmarks.utils.benchmark and renders throughput-vs-concurrency
and latency-percentile charts. Falls back to a text summary when matplotlib
is unavailable.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
from typing import List


def _load_summaries(data_dir: str) -> List[dict]:
    out = []
    for path in sorted(glob.glob(os.path.join(data_dir, "*_summary.json"))):
        with open(path) as f:
            out.append(json.load(f))
    return out


def _text_report(reports: List[dict], data_dir: str) -> str:
    lines = []
    for rep in reports:
        lines.append(f"benchmark: {rep['benchmark_name']}  model: {rep['model']}")
        lines.append(f"{'conc':>6} {'tok/s':>10} {'tok/s/chip':>11} "
                     f"{'ttft p50':>9} {'itl p50':>8} {'fail':>5}")
        for s in rep["sweep"]:
            lines.append(
                f"{s['concurrency']:>6} {s['output_tok_per_s']:>10} "
                f"{s['output_tok_per_s_per_chip']:>11} "
                f"{s['ttft_ms']['p50']:>8}ms {s['itl_ms']['p50']:>7}ms "
                f"{s['failed']:>5}"
            )
        lines.append("")
    text = "\n".join(lines)
    path = os.path.join(data_dir, "report.txt")
    with open(path, "w") as f:
        f.write(text)
    return text


def _charts(reports: List[dict], data_dir: str) -> None:
    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    for rep in reports:
        sweep = rep["sweep"]
        conc = [s["concurrency"] for s in sweep]
        fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(11, 4))
        ax1.plot(conc, [s["output_tok_per_s"] for s in sweep], marker="o")
        ax1.set_xlabel("concurrency")
        ax1.set_ylabel("output tok/s")
        ax1.set_title(f"{rep['benchmark_name']}: throughput")
        ax2.plot(conc, [s["ttft_ms"]["p50"] for s in sweep], marker="o",
                 label="TTFT p50 (ms)")
        ax2.plot(conc, [s["itl_ms"]["p50"] for s in sweep], marker="s",
                 label="ITL p50 (ms)")
        ax2.set_xlabel("concurrency")
        ax2.set_ylabel("latency (ms)")
        ax2.set_title("latency")
        ax2.legend()
        fig.tight_layout()
        out = os.path.join(data_dir, f"{rep['benchmark_name']}.png")
        fig.savefig(out, dpi=120)
        plt.close(fig)
        print(f"[plot] wrote {out}")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="benchmarks.utils.plot")
    p.add_argument("--data-dir", required=True)
    args = p.parse_args(argv)

    reports = _load_summaries(args.data_dir)
    if not reports:
        print(f"[plot] no *_summary.json files in {args.data_dir}")
        return 1
    print(_text_report(reports, args.data_dir))
    try:
        _charts(reports, args.data_dir)
    except Exception as e:  # matplotlib missing or headless failure
        print(f"[plot] charts skipped ({type(e).__name__}: {e}); "
              f"text report written")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
