#!/usr/bin/env bash
# Install the Dynamo-TPU platform onto an existing cluster.
#
# Layer 2 of the stack (SURVEY.md §1 L2). Contract-compatible with the
# reference's install-dynamo-1node.sh: same ordering (StorageClass -> CRDs ->
# platform -> accelerator plugin -> allocatable verification), same readiness
# gates (etcd-0 / nats-0 / operator), same env-knob style — with the NVIDIA
# GPU Operator swapped for the TPU device plugin and the allocatable poll
# checking `google.com/tpu` instead of `nvidia.com/gpu`
# (/root/reference/install-dynamo-1node.sh:282-321).
#
# Usage: ./install-dynamo-1node.sh    (or: make dynamo)
set -euo pipefail

# ---- configuration (env-overridable) ----------------------------------------
NAMESPACE="${NAMESPACE:-dynamo-system}"
RELEASE_VERSION="${RELEASE_VERSION:-local}"     # "local" applies deploy/ from this repo
# Runtime image for the operator AND the default for materialized workers
# (built by `make image`; the analogue of the reference's consumed
# nvcr.io/nvidia/ai-dynamo/*-runtime images)
DYNAMO_IMAGE="${DYNAMO_IMAGE:-dynamo-tpu/runtime:${RELEASE_VERSION/#local/latest}}"
NAMESPACE_RESTRICTED_OPERATOR="${NAMESPACE_RESTRICTED_OPERATOR:-false}"
ENABLE_GANG_SCHEDULING="${ENABLE_GANG_SCHEDULING:-false}"   # Grove/KAI analogue
PROMETHEUS_ENDPOINT="${PROMETHEUS_ENDPOINT:-http://prometheus-kube-prometheus-prometheus.monitoring.svc.cluster.local:9090}"
INSTALL_TPU_PLUGIN="${INSTALL_TPU_PLUGIN:-true}"
# standalone exporter DaemonSet is a debug fallback only — the primary
# hardware-metrics path is in-process in the engine workers
INSTALL_TPU_EXPORTER="${INSTALL_TPU_EXPORTER:-false}"
TPU_REQUIRED="${TPU_REQUIRED:-false}"           # hard-fail if no google.com/tpu allocatable
TPU_POLL_RETRIES="${TPU_POLL_RETRIES:-120}"
TPU_POLL_INTERVAL="${TPU_POLL_INTERVAL:-5}"
WAIT_TIMEOUT="${WAIT_TIMEOUT:-600s}"
REPO_ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"

log() { echo "[$(date +%H:%M:%S)] $*"; }
die() { echo "ERROR: $*" >&2; exit 1; }

# ---- preflight --------------------------------------------------------------
for cmd in kubectl; do
  command -v "$cmd" >/dev/null 2>&1 || die "missing required command: $cmd"
done
kubectl cluster-info >/dev/null 2>&1 || die "cluster unreachable (is kubeconfig set?)"
[[ -n "$RELEASE_VERSION" ]] || die "RELEASE_VERSION must be set"

# ---- step 1: default StorageClass -------------------------------------------
# etcd/NATS PVCs (and the model-cache PVC) need a default StorageClass on a
# single node; install rancher local-path-provisioner if none is default.
default_sc="$(kubectl get storageclass -o \
  jsonpath='{range .items[*]}{.metadata.name}{"\t"}{.metadata.annotations.storageclass\.kubernetes\.io/is-default-class}{"\n"}{end}' \
  | awk '$2=="true"{print $1; exit}')"
if [[ -n "$default_sc" ]]; then
  log "default StorageClass present: ${default_sc}"
else
  log "installing local-path-provisioner as default StorageClass"
  kubectl apply -f https://raw.githubusercontent.com/rancher/local-path-provisioner/v0.0.30/deploy/local-path-storage.yaml
  kubectl patch storageclass local-path -p \
    '{"metadata":{"annotations":{"storageclass.kubernetes.io/is-default-class":"true"}}}'
fi

# ---- release resolution ------------------------------------------------------
# RELEASE_VERSION=local applies deploy/ from this checkout (dev default).
# Anything else installs a PINNED bundle — dist/dynamo-tpu-install-<ver>.yaml
# built by `make release-manifests`, or fetched from the release mirror
# (DYNAMO_RELEASE_BASE_URL) — the analogue of the reference's versioned
# chart fetch (/root/reference/install-dynamo-1node.sh:182,198).
RELEASE_BUNDLE=""
GANG_MANIFEST="${REPO_ROOT}/deploy/gang-scheduler.yaml"
resolve_release_artifact() {  # $1 = artifact file name; echoes a local path
  local name="$1" local_path url tmp
  local_path="${REPO_ROOT}/dist/${name}"
  if [[ -f "$local_path" ]]; then
    echo "$local_path"
    return 0
  fi
  url="${DYNAMO_RELEASE_BASE_URL:-https://github.com/dynamo-tpu/dynamo-tpu/releases/download}/${RELEASE_VERSION}/${name}"
  tmp="$(mktemp "/tmp/${name}.XXXX")"
  log "fetching ${url}" >&2
  curl -fsSL -o "$tmp" "$url" || die "release artifact fetch failed: ${url}
(build it locally with: make release-manifests RELEASE_VERSION=${RELEASE_VERSION})"
  echo "$tmp"
}
if [[ "$RELEASE_VERSION" != "local" ]]; then
  RELEASE_BUNDLE="$(resolve_release_artifact "dynamo-tpu-install-${RELEASE_VERSION}.yaml")"
  if [[ "$ENABLE_GANG_SCHEDULING" == "true" ]]; then
    # pinned release must pin the gang scheduler too — a fetch miss is an
    # error, not a silent fallback to the (possibly newer) checkout copy
    GANG_MANIFEST="$(resolve_release_artifact "gang-scheduler-${RELEASE_VERSION}.yaml")"
  fi
fi

# ---- step 2: CRDs ------------------------------------------------------------
if [[ -z "$RELEASE_BUNDLE" ]]; then
  log "installing Dynamo-TPU CRDs (release: ${RELEASE_VERSION})"
  kubectl apply -f "${REPO_ROOT}/deploy/crds/"
fi

# ---- step 3: platform (operator + etcd + NATS) -------------------------------
log "installing platform into namespace ${NAMESPACE}"
kubectl create namespace "$NAMESPACE" --dry-run=client -o yaml | kubectl apply -f -

# The operator Deployment lives in the namespace hardcoded by operator.yaml
# (its RBAC + ServiceAccount are bound there), independent of $NAMESPACE.
OPERATOR_NAMESPACE="dynamo-system"
operator_env=("PROMETHEUS_ENDPOINT=${PROMETHEUS_ENDPOINT}"
              "DYNAMO_TPU_DEFAULT_IMAGE=${DYNAMO_IMAGE}")
if [[ "$NAMESPACE_RESTRICTED_OPERATOR" == "true" ]]; then
  operator_env+=("WATCH_NAMESPACE=${NAMESPACE}")
fi
if [[ "$ENABLE_GANG_SCHEDULING" == "true" ]]; then
  operator_env+=("ENABLE_GANG_SCHEDULING=true")
  # install the coscheduling second scheduler (PodGroup CRD + deployment)
  # BEFORE the operator env lands: materialized multi-pod workers reference
  # schedulerName scheduler-plugins-scheduler, which must exist or their
  # pods sit Pending forever. Grove/KAI analogue
  # (/root/reference/install-dynamo-1node.sh:207-212).
  log "installing gang (coscheduling) scheduler"
  kubectl apply -f "$GANG_MANIFEST"
  kubectl wait -n scheduler-plugins --for=condition=Available \
    deployment/scheduler-plugins-scheduler --timeout="$WAIT_TIMEOUT" \
    || log "WARN: gang scheduler not Available yet; gang pods stay Pending until it is"
fi

if [[ -n "$RELEASE_BUNDLE" ]]; then
  # pinned bundle: CRDs + platform + operator in one versioned stream;
  # namespace-less docs land in $NAMESPACE, explicit ones keep their own.
  # DYNAMO_IMAGE still wins (private-registry mirrors): swap the bundle's
  # pinned ref the same way the local path swaps the dev tag.
  log "applying pinned release bundle ${RELEASE_VERSION} (image ${DYNAMO_IMAGE})"
  sed "s|dynamo-tpu/runtime:${RELEASE_VERSION}|${DYNAMO_IMAGE}|g" \
    "$RELEASE_BUNDLE" | kubectl apply -n "$NAMESPACE" -f -
else
  kubectl apply -n "$NAMESPACE" -f "${REPO_ROOT}/deploy/platform/"
  # operator.yaml carries its own namespace refs; apply then inject env
  # config. The image ref is parameterized: the checked-in manifest pins
  # the :latest dev tag, sed swaps in $DYNAMO_IMAGE.
  log "operator image: ${DYNAMO_IMAGE}"
  sed "s|dynamo-tpu/runtime:latest|${DYNAMO_IMAGE}|g" \
    "${REPO_ROOT}/deploy/operator.yaml" | kubectl apply -f -
fi
kubectl set env -n "$OPERATOR_NAMESPACE" \
  deployment/dynamo-tpu-operator-controller-manager "${operator_env[@]}" >/dev/null

# ---- step 4: readiness gates -------------------------------------------------
log "waiting for platform pods (timeout ${WAIT_TIMEOUT} each)"
kubectl wait -n "$NAMESPACE" --for=condition=Ready pod/dynamo-platform-etcd-0 \
  --timeout="$WAIT_TIMEOUT"
kubectl wait -n "$NAMESPACE" --for=condition=Ready pod/dynamo-platform-nats-0 \
  --timeout="$WAIT_TIMEOUT"
kubectl wait -n "$OPERATOR_NAMESPACE" --for=condition=Available \
  deployment/dynamo-tpu-operator-controller-manager --timeout="$WAIT_TIMEOUT"

# ---- step 5: TPU device plugin + metrics exporter ----------------------------
# Separate versioned artifacts in release mode, so these knobs keep working
# against a pinned install exactly as they do against the checkout.
if [[ "$INSTALL_TPU_PLUGIN" == "true" ]]; then
  log "installing TPU device plugin DaemonSet"
  if [[ -n "$RELEASE_BUNDLE" ]]; then
    kubectl apply -f "$(resolve_release_artifact "tpu-device-plugin-${RELEASE_VERSION}.yaml")"
  else
    kubectl apply -f "${REPO_ROOT}/deploy/tpu-device-plugin.yaml"
  fi
fi
if [[ "$INSTALL_TPU_EXPORTER" == "true" ]]; then
  log "installing TPU metrics exporter DaemonSet"
  if [[ -n "$RELEASE_BUNDLE" ]]; then
    sed "s|dynamo-tpu/runtime:${RELEASE_VERSION}|${DYNAMO_IMAGE}|g" \
      "$(resolve_release_artifact "tpu-metrics-exporter-${RELEASE_VERSION}.yaml")" \
      | kubectl apply -f -
  else
    sed "s|dynamo-tpu/runtime:latest|${DYNAMO_IMAGE}|g" \
      "${REPO_ROOT}/deploy/tpu-metrics-exporter.yaml" | kubectl apply -f -
  fi
fi

# ---- step 6: verify google.com/tpu allocatable -------------------------------
# Mirror of the reference's nvidia.com/gpu allocatable poll
# (/root/reference/install-dynamo-1node.sh:305-321). On GKE TPU node pools the
# built-in plugin advertises the resource; on CPU-only dev clusters the poll
# is skipped unless TPU_REQUIRED=true.
count_tpus() {
  kubectl get nodes -o jsonpath='{range .items[*]}{.status.allocatable.google\.com/tpu}{"\n"}{end}' \
    | awk 'BEGIN{s=0} /^[0-9]+$/{s+=$1} END{print s}'
}

if [[ "$TPU_REQUIRED" == "true" ]]; then
  log "polling for google.com/tpu allocatable (${TPU_POLL_RETRIES}x${TPU_POLL_INTERVAL}s)"
  tpus=0
  for ((i = 1; i <= TPU_POLL_RETRIES; i++)); do
    tpus="$(count_tpus)"
    [[ "$tpus" -gt 0 ]] && break
    sleep "$TPU_POLL_INTERVAL"
  done
  [[ "$tpus" -gt 0 ]] || die "no google.com/tpu allocatable after $((TPU_POLL_RETRIES * TPU_POLL_INTERVAL))s"
  log "google.com/tpu allocatable: ${tpus}"
else
  tpus="$(count_tpus)"
  if [[ "$tpus" -gt 0 ]]; then
    log "google.com/tpu allocatable: ${tpus}"
  else
    log "no TPUs allocatable (CPU-only cluster?) — continuing; set TPU_REQUIRED=true to enforce"
  fi
fi

log "Dynamo-TPU platform installed. Next:"
echo "    ./deploy-incluster.sh --manifest examples/deploy/jetstream/agg.yaml"
