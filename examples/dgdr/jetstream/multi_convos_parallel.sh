#!/usr/bin/env bash
# Parallel conversation load test: N conversations x 3 scripted turns, with
# bounded concurrency via a FIFO-fd token semaphore.
#
# Layer 5 of the stack (SURVEY.md §1 L5); contract mirrors the reference's
# examples/dgdr/trtllm/multi_convos_parallel.sh (NUM_CONVOS / CONCURRENCY env
# knobs, per-conversation JSON history, transcript collection, failure
# aggregation with nonzero exit when any conversation fails).
#
# Usage: DYNAMO_BASE_URL=http://<ip>:<port> ./multi_convos_parallel.sh
set -uo pipefail

BASE_URL="${DYNAMO_BASE_URL:-http://127.0.0.1:8000}"
MODEL="${MODEL:-}"
NUM_CONVOS="${NUM_CONVOS:-8}"
CONCURRENCY="${CONCURRENCY:-4}"
MAX_TOKENS="${MAX_TOKENS:-128}"
OUT_DIR="${OUT_DIR:-$(mktemp -d /tmp/dynamo-convos.XXXXXX)}"

die() { echo "multi_convos: $*" >&2; exit 1; }
command -v curl >/dev/null || die "curl required"
command -v python3 >/dev/null || die "python3 required"

if [[ -z "$MODEL" ]]; then
  MODEL="$(curl -fsS "${BASE_URL}/v1/models" \
    | python3 -c 'import json,sys; print(json.load(sys.stdin)["data"][0]["id"])')" \
    || die "cannot reach ${BASE_URL}/v1/models"
fi
mkdir -p "$OUT_DIR"
echo "model=${MODEL} convos=${NUM_CONVOS} concurrency=${CONCURRENCY} out=${OUT_DIR}"

# The three scripted turns every conversation walks through.
TURNS=(
  "Give me one sentence about the ocean."
  "Now make it about mountains instead."
  "Combine both sentences into one."
)

# chat_once HISTORY_FILE PROMPT -> appends to history, prints assistant text
chat_once() {
  local hist="$1" prompt="$2"
  python3 - "$hist" user "$prompt" <<'PY'
import json, sys
p, role, content = sys.argv[1:4]
h = json.load(open(p)); h.append({"role": role, "content": content})
json.dump(h, open(p, "w"))
PY
  local body
  body="$(python3 - "$MODEL" "$MAX_TOKENS" "$hist" <<'PY'
import json, sys
model, max_toks, hist = sys.argv[1:4]
print(json.dumps({"model": model, "messages": json.load(open(hist)),
                  "temperature": 0, "max_tokens": int(max_toks)}))
PY
)"
  local reply
  reply="$(curl -fsS --max-time 300 "${BASE_URL}/v1/chat/completions" \
    -H 'Content-Type: application/json' -d "$body" \
    | python3 -c 'import json,sys; print(json.load(sys.stdin)["choices"][0]["message"]["content"])')" \
    || return 1
  python3 - "$hist" assistant "$reply" <<'PY'
import json, sys
p, role, content = sys.argv[1:4]
h = json.load(open(p)); h.append({"role": role, "content": content})
json.dump(h, open(p, "w"))
PY
  printf '%s\n' "$reply"
}

run_convo() {
  local id="$1"
  local hist="${OUT_DIR}/convo-${id}.json"
  local transcript="${OUT_DIR}/convo-${id}.txt"
  echo "[]" >"$hist"
  local turn reply
  for turn in "${TURNS[@]}"; do
    {
      echo "user> ${turn}"
      if ! reply="$(chat_once "$hist" "$turn")"; then
        echo "FAILED at turn: ${turn}"
        return 1
      fi
      echo "model> ${reply}"
    } >>"$transcript"
  done
}

# ---- FIFO-fd token semaphore -------------------------------------------------
SEM="$(mktemp -u /tmp/dynamo-sem.XXXXXX)"
mkfifo "$SEM"
exec 3<>"$SEM"
rm -f "$SEM"
for ((i = 0; i < CONCURRENCY; i++)); do printf '.' >&3; done
sem_acquire() { local _t; read -r -n1 -u3 _t; }
sem_release() { printf '.' >&3; }

pids=()
for ((c = 1; c <= NUM_CONVOS; c++)); do
  sem_acquire
  {
    if run_convo "$c"; then
      touch "${OUT_DIR}/convo-${c}.ok"
    fi
    sem_release
  } &
  pids+=($!)
done
wait "${pids[@]}" 2>/dev/null

# ---- aggregate ---------------------------------------------------------------
ok=0 failed=0
for ((c = 1; c <= NUM_CONVOS; c++)); do
  if [[ -f "${OUT_DIR}/convo-${c}.ok" ]]; then
    ok=$((ok + 1))
  else
    failed=$((failed + 1))
    echo "FAILED: conversation ${c} (transcript: ${OUT_DIR}/convo-${c}.txt)"
  fi
done
echo "done: ${ok}/${NUM_CONVOS} conversations succeeded (transcripts in ${OUT_DIR})"
[[ $failed -eq 0 ]]
