#!/usr/bin/env bash
# Apply the SLA-driven DGDR workflow: template ConfigMap -> DGDR -> (operator
# profiles + generates + applies the DGD) -> fixed NodePort + test snippet.
# Mirror of /root/reference/examples/dgdr/trtllm/run-dgdr.sh.
set -euo pipefail

NAMESPACE="${NAMESPACE:-dynamo}"
NODEPORT="${NODEPORT:-30081}"
HERE="$(cd "$(dirname "${BASH_SOURCE[0]}")" && pwd)"

log() { echo "[run-dgdr] $*"; }

kubectl create namespace "$NAMESPACE" --dry-run=client -o yaml | kubectl apply -f - >/dev/null

# Template ConfigMap: key MUST be disagg.yaml to match the DGDR's
# profilingConfig.config.configMapRef.key.
log "creating/updating template ConfigMap qwen-config"
kubectl create configmap qwen-config -n "$NAMESPACE" \
  --from-file=disagg.yaml="${HERE}/disagg.yaml" \
  --dry-run=client -o yaml | kubectl apply -f -

log "applying DGDR"
kubectl apply -n "$NAMESPACE" -f "${HERE}/dgdr.yaml"

# Pin the frontend service (created later by the generated DGD) to a fixed
# NodePort once it exists.
log "waiting for generated frontend service"
frontend=""
for _ in $(seq 1 120); do
  frontend="$(kubectl get svc -n "$NAMESPACE" \
    -l tpu.dynamo.ai/component-type=frontend \
    -o jsonpath='{.items[0].metadata.name}' 2>/dev/null || true)"
  [[ -n "$frontend" ]] && break
  sleep 5
done
if [[ -n "$frontend" ]]; then
  kubectl patch svc -n "$NAMESPACE" "$frontend" -p \
    "{\"spec\":{\"type\":\"NodePort\",\"ports\":[{\"port\":8000,\"targetPort\":8000,\"nodePort\":${NODEPORT}}]}}"
else
  log "WARN: frontend service not created yet; patch it manually once the profile completes"
fi

node_ip="$(kubectl get nodes -o jsonpath='{.items[0].status.addresses[?(@.type=="InternalIP")].address}')"
cat <<EOF

DGDR applied. Once profiling finishes and the generated DGD is ready:
  export DYNAMO_BASE_URL=http://${node_ip}:${NODEPORT}
  curl \$DYNAMO_BASE_URL/v1/models
  curl -s \$DYNAMO_BASE_URL/v1/chat/completions -H 'Content-Type: application/json' \\
    -d '{"model": "Qwen/Qwen3-0.6B", "messages": [{"role": "user", "content": "hello"}], "max_tokens": 32}'
EOF
